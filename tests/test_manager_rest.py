"""Manager REST API (manager/rest.py): CRUD surface, bearer-token roles,
and model activation — the reference's manager/handlers + casbin RBAC
shape (router.go:269, service/model.go:109)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.models_registry import ModelRegistry
from dragonfly2_tpu.manager.objectstorage import FSObjectStorage
from dragonfly2_tpu.manager.rest import RestServer
from dragonfly2_tpu.manager.service import ManagerService


@pytest.fixture
def rest(tmp_path):
    db = Database(tmp_path / "m.db")
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "obj"))
    service = ManagerService(db, models)
    server = RestServer(
        service, tokens={"admin-tok": "admin", "guest-tok": "guest"}
    )
    addr = server.start()
    yield {"addr": addr, "db": db, "models": models, "service": service}
    server.stop()
    db.close()


def call(addr, method, path, body=None, token="admin-tok"):
    req = urllib.request.Request(
        f"http://{addr}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}"} if token else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_and_auth(rest):
    addr = rest["addr"]
    status, body = call(addr, "GET", "/healthy")
    assert status == 200 and body["status"] == "ok"
    # no token → 401
    status, body = call(addr, "GET", "/api/v1/schedulers", token=None)
    assert status == 401
    # bad token → 401
    status, body = call(addr, "GET", "/api/v1/schedulers", token="nope")
    assert status == 401
    # guest can read
    status, body = call(addr, "GET", "/api/v1/schedulers", token="guest-tok")
    assert status == 200 and body == []
    # guest cannot write
    status, body = call(
        addr, "POST", "/api/v1/scheduler-clusters", {"name": "x"}, token="guest-tok"
    )
    assert status == 403


def test_cluster_crud(rest):
    addr = rest["addr"]
    status, created = call(
        addr,
        "POST",
        "/api/v1/scheduler-clusters",
        {"name": "cluster-2", "config": {"candidate_parent_limit": 7}},
    )
    assert status == 200
    cid = created["id"]
    status, got = call(addr, "GET", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 200 and got["name"] == "cluster-2"
    assert json.loads(got["config"])["candidate_parent_limit"] == 7
    status, updated = call(
        addr, "PATCH", f"/api/v1/scheduler-clusters/{cid}", {"config": {"a": 1}}
    )
    assert status == 200 and json.loads(updated["config"]) == {"a": 1}
    status, _ = call(addr, "DELETE", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 200
    status, _ = call(addr, "GET", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 404


def test_jobs_roundtrip(rest):
    addr = rest["addr"]
    status, job = call(
        addr,
        "POST",
        "/api/v1/jobs",
        {"type": "preheat", "args": {"url": "https://x/blob"}, "scheduler_cluster_id": 1},
    )
    assert status == 200 and job["state"] == "queued"
    status, got = call(addr, "GET", f"/api/v1/jobs/{job['id']}")
    assert status == 200 and got["type"] == "preheat"
    status, jobs = call(addr, "GET", "/api/v1/jobs")
    assert status == 200 and len(jobs) == 1


def test_model_activation_flow(rest):
    """Upload two versions via the registry, flip activation through
    REST, verify the previously-active version deactivates (reference
    updateModelStateToActive version flip)."""
    addr = rest["addr"]
    models: ModelRegistry = rest["models"]
    weights = np.arange(4, dtype=np.float32).tobytes()
    models.create("mlp-host-1", "mlp", weights, {"mse": 0.5}, ip="1.2.3.4",
                  hostname="h1", scheduler_cluster_id=1)
    models.create("mlp-host-1", "mlp", weights, {"mse": 0.4}, ip="1.2.3.4",
                  hostname="h1", scheduler_cluster_id=1)

    status, listed = call(addr, "GET", "/api/v1/models?scheduler_cluster_id=1")
    assert status == 200 and len(listed) == 2
    assert all(m["state"] == "inactive" for m in listed)

    status, act = call(
        addr, "PUT", "/api/v1/models/mlp-host-1/versions/1/state", {"state": "active"}
    )
    assert status == 200 and act["state"] == "active"

    status, act2 = call(
        addr, "PUT", "/api/v1/models/mlp-host-1/versions/2/state", {"state": "active"}
    )
    assert status == 200 and act2["state"] == "active"
    # version 1 flipped back to inactive
    status, v1 = call(addr, "GET", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 200 and v1["state"] == "inactive"

    status, _ = call(addr, "DELETE", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 200
    status, _ = call(addr, "GET", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 404


def test_applications(rest):
    addr = rest["addr"]
    status, app = call(
        addr, "POST", "/api/v1/applications",
        {"name": "registry", "url": "https://r.io", "priority": {"level": 3}},
    )
    assert status == 200
    status, apps = call(addr, "GET", "/api/v1/applications")
    assert status == 200 and apps[0]["name"] == "registry"


def test_open_mode_without_tokens(tmp_path):
    db = Database(tmp_path / "m.db")
    service = ManagerService(db, ModelRegistry(db, FSObjectStorage(tmp_path / "o")))
    server = RestServer(service)  # no tokens = dev mode
    addr = server.start()
    try:
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=None)
        assert status == 200
    finally:
        server.stop()
        db.close()


def test_healthy_is_unauthenticated(rest):
    status, body = call(rest["addr"], "GET", "/healthy", token=None)
    assert status == 200 and body["status"] == "ok"


def test_bad_path_param_is_client_error(rest):
    status, body = call(rest["addr"], "GET", "/api/v1/schedulers/abc")
    assert status == 400


def test_deactivate_stamps_updated_at(rest):
    import numpy as np

    models = rest["models"]
    models.create("m1", "mlp", b"\x00", {"mse": 1.0}, scheduler_cluster_id=1)
    models.activate("m1", 1)
    before = models.get("m1", 1).updated_at
    import time

    time.sleep(0.01)
    status, row = call(
        rest["addr"], "PUT", "/api/v1/models/m1/versions/1/state", {"state": "inactive"}
    )
    assert status == 200 and row["state"] == "inactive"
    assert models.get("m1", 1).updated_at > before


def test_console_served_at_root(rest):
    """The embedded console page is served at / and /console without auth
    (static asset; its data calls carry the token — reference embeds its
    React console the same way, manager/manager.go:61-85)."""
    for path in ("/", "/console"):
        req = urllib.request.Request(f"http://{rest['addr']}{path}")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
    # the page drives the same REST surface this test drives
    assert "/api/v1/scheduler-clusters" in page
    assert "/api/v1/models" in page
    assert "setModelState" in page


def test_users_and_pats(tmp_path):
    """DB-backed users + personal access tokens: bootstrap in dev mode,
    then auth flips on — PATs and signin tokens resolve to roles, config
    tokens keep working (reference manager users/PAT surface)."""
    db = Database(tmp_path / "u.db")
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "obj"))
    server = RestServer(ManagerService(db, models))  # no config tokens
    addr = server.start()
    try:
        # dev mode: open admin until the first user exists
        status, _ = call(addr, "GET", "/api/v1/users", token=None)
        assert status == 200
        status, admin = call(
            addr, "POST", "/api/v1/users",
            {"name": "root", "password": "s3cret", "role": "admin"}, token=None,
        )
        assert status == 200 and "password_hash" not in admin
        # auth is now enforced
        status, _ = call(addr, "GET", "/api/v1/users", token=None)
        assert status == 401
        # signin exchanges the password for a session token
        status, session = call(
            addr, "POST", "/api/v1/users/signin",
            {"name": "root", "password": "s3cret"}, token=None,
        )
        assert status == 200 and session["role"] == "admin"
        tok = session["token"]
        # bad password refused
        status, _ = call(
            addr, "POST", "/api/v1/users/signin",
            {"name": "root", "password": "wrong"}, token=None,
        )
        assert status == 401
        # the session token authenticates as admin
        status, _ = call(addr, "GET", "/api/v1/users", token=tok)
        assert status == 200
        # mint a guest user + PAT: read-only enforcement
        status, guest = call(
            addr, "POST", "/api/v1/users",
            {"name": "viewer", "password": "pw", "role": "guest"}, token=tok,
        )
        status, pat = call(
            addr, "POST", f"/api/v1/users/{guest['id']}/personal-access-tokens",
            {"name": "ci"}, token=tok,
        )
        assert status == 200 and pat["token"].startswith("dfp_")
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat["token"])
        assert status == 200
        status, _ = call(
            addr, "POST", "/api/v1/scheduler-clusters", {"name": "x"},
            token=pat["token"],
        )
        assert status == 403  # guest is read-only
        # revocation kills the token
        status, _ = call(
            addr, "DELETE",
            f"/api/v1/users/{guest['id']}/personal-access-tokens/{pat['id']}",
            token=tok,
        )
        assert status == 200
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat["token"])
        assert status == 401
        # disabling a user kills their remaining tokens
        status, pat2 = call(
            addr, "POST", f"/api/v1/users/{guest['id']}/personal-access-tokens",
            {"name": "ci2"}, token=tok,
        )
        call(addr, "PATCH", f"/api/v1/users/{guest['id']}", {"state": "disabled"}, token=tok)
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat2["token"])
        assert status == 401
    finally:
        server.stop()
        db.close()
