"""Manager REST API (manager/rest.py): CRUD surface, bearer-token roles,
and model activation — the reference's manager/handlers + casbin RBAC
shape (router.go:269, service/model.go:109)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.models_registry import ModelRegistry
from dragonfly2_tpu.manager.objectstorage import FSObjectStorage
from dragonfly2_tpu.manager.rest import RestServer
from dragonfly2_tpu.manager.service import ManagerService


@pytest.fixture
def rest(tmp_path):
    db = Database(tmp_path / "m.db")
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "obj"))
    service = ManagerService(db, models)
    server = RestServer(
        service, tokens={"admin-tok": "admin", "guest-tok": "guest"}
    )
    addr = server.start()
    yield {"addr": addr, "db": db, "models": models, "service": service}
    server.stop()
    db.close()


def call(addr, method, path, body=None, token="admin-tok"):
    req = urllib.request.Request(
        f"http://{addr}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}"} if token else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_and_auth(rest):
    addr = rest["addr"]
    status, body = call(addr, "GET", "/healthy")
    assert status == 200 and body["status"] == "ok"
    # no token → 401
    status, body = call(addr, "GET", "/api/v1/schedulers", token=None)
    assert status == 401
    # bad token → 401
    status, body = call(addr, "GET", "/api/v1/schedulers", token="nope")
    assert status == 401
    # guest can read
    status, body = call(addr, "GET", "/api/v1/schedulers", token="guest-tok")
    assert status == 200 and body == []
    # guest cannot write
    status, body = call(
        addr, "POST", "/api/v1/scheduler-clusters", {"name": "x"}, token="guest-tok"
    )
    assert status == 403


def test_cluster_crud(rest):
    addr = rest["addr"]
    status, created = call(
        addr,
        "POST",
        "/api/v1/scheduler-clusters",
        {"name": "cluster-2", "config": {"candidate_parent_limit": 7}},
    )
    assert status == 200
    cid = created["id"]
    status, got = call(addr, "GET", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 200 and got["name"] == "cluster-2"
    assert json.loads(got["config"])["candidate_parent_limit"] == 7
    status, updated = call(
        addr, "PATCH", f"/api/v1/scheduler-clusters/{cid}", {"config": {"a": 1}}
    )
    assert status == 200 and json.loads(updated["config"]) == {"a": 1}
    status, _ = call(addr, "DELETE", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 200
    status, _ = call(addr, "GET", f"/api/v1/scheduler-clusters/{cid}")
    assert status == 404


def test_jobs_roundtrip(rest):
    addr = rest["addr"]
    status, job = call(
        addr,
        "POST",
        "/api/v1/jobs",
        {"type": "preheat", "args": {"url": "https://x/blob"}, "scheduler_cluster_id": 1},
    )
    assert status == 200 and job["state"] == "queued"
    status, got = call(addr, "GET", f"/api/v1/jobs/{job['id']}")
    assert status == 200 and got["type"] == "preheat"
    status, jobs = call(addr, "GET", "/api/v1/jobs")
    assert status == 200 and len(jobs) == 1


def test_model_activation_flow(rest):
    """Upload two versions via the registry, flip activation through
    REST, verify the previously-active version deactivates (reference
    updateModelStateToActive version flip)."""
    addr = rest["addr"]
    models: ModelRegistry = rest["models"]
    weights = np.arange(4, dtype=np.float32).tobytes()
    models.create("mlp-host-1", "mlp", weights, {"mse": 0.5}, ip="1.2.3.4",
                  hostname="h1", scheduler_cluster_id=1)
    models.create("mlp-host-1", "mlp", weights, {"mse": 0.4}, ip="1.2.3.4",
                  hostname="h1", scheduler_cluster_id=1)

    status, listed = call(addr, "GET", "/api/v1/models?scheduler_cluster_id=1")
    assert status == 200 and len(listed) == 2
    assert all(m["state"] == "inactive" for m in listed)

    status, act = call(
        addr, "PUT", "/api/v1/models/mlp-host-1/versions/1/state", {"state": "active"}
    )
    assert status == 200 and act["state"] == "active"

    status, act2 = call(
        addr, "PUT", "/api/v1/models/mlp-host-1/versions/2/state", {"state": "active"}
    )
    assert status == 200 and act2["state"] == "active"
    # version 1 flipped back to inactive
    status, v1 = call(addr, "GET", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 200 and v1["state"] == "inactive"

    status, _ = call(addr, "DELETE", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 200
    status, _ = call(addr, "GET", "/api/v1/models/mlp-host-1/versions/1")
    assert status == 404


def test_applications(rest):
    addr = rest["addr"]
    status, app = call(
        addr, "POST", "/api/v1/applications",
        {"name": "registry", "url": "https://r.io", "priority": {"level": 3}},
    )
    assert status == 200
    status, apps = call(addr, "GET", "/api/v1/applications")
    assert status == 200 and apps[0]["name"] == "registry"


def test_open_mode_without_tokens(tmp_path):
    db = Database(tmp_path / "m.db")
    service = ManagerService(db, ModelRegistry(db, FSObjectStorage(tmp_path / "o")))
    server = RestServer(service)  # no tokens = dev mode
    addr = server.start()
    try:
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=None)
        assert status == 200
    finally:
        server.stop()
        db.close()


def test_healthy_is_unauthenticated(rest):
    status, body = call(rest["addr"], "GET", "/healthy", token=None)
    assert status == 200 and body["status"] == "ok"


def test_bad_path_param_is_client_error(rest):
    status, body = call(rest["addr"], "GET", "/api/v1/schedulers/abc")
    assert status == 400


def test_deactivate_stamps_updated_at(rest):
    import numpy as np

    models = rest["models"]
    models.create("m1", "mlp", b"\x00", {"mse": 1.0}, scheduler_cluster_id=1)
    models.activate("m1", 1)
    before = models.get("m1", 1).updated_at
    import time

    time.sleep(0.01)
    status, row = call(
        rest["addr"], "PUT", "/api/v1/models/m1/versions/1/state", {"state": "inactive"}
    )
    assert status == 200 and row["state"] == "inactive"
    assert models.get("m1", 1).updated_at > before


def test_console_served_at_root(rest):
    """The embedded console page is served at / and /console without auth
    (static asset; its data calls carry the token — reference embeds its
    React console the same way, manager/manager.go:61-85)."""
    for path in ("/", "/console"):
        req = urllib.request.Request(f"http://{rest['addr']}{path}")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
    # the page drives the same REST surface this test drives
    assert "/api/v1/scheduler-clusters" in page
    assert "/api/v1/models" in page
    assert "setModelState" in page


def test_users_and_pats(tmp_path):
    """DB-backed users + personal access tokens: bootstrap in dev mode,
    then auth flips on — PATs and signin tokens resolve to roles, config
    tokens keep working (reference manager users/PAT surface)."""
    db = Database(tmp_path / "u.db")
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "obj"))
    server = RestServer(ManagerService(db, models))  # no config tokens
    addr = server.start()
    try:
        # dev mode: open admin until the first user exists
        status, _ = call(addr, "GET", "/api/v1/users", token=None)
        assert status == 200
        status, admin = call(
            addr, "POST", "/api/v1/users",
            {"name": "root", "password": "s3cret", "role": "admin"}, token=None,
        )
        assert status == 200 and "password_hash" not in admin
        # auth is now enforced
        status, _ = call(addr, "GET", "/api/v1/users", token=None)
        assert status == 401
        # signin exchanges the password for a session token
        status, session = call(
            addr, "POST", "/api/v1/users/signin",
            {"name": "root", "password": "s3cret"}, token=None,
        )
        assert status == 200 and session["role"] == "admin"
        tok = session["token"]
        # bad password refused
        status, _ = call(
            addr, "POST", "/api/v1/users/signin",
            {"name": "root", "password": "wrong"}, token=None,
        )
        assert status == 401
        # the session token authenticates as admin
        status, _ = call(addr, "GET", "/api/v1/users", token=tok)
        assert status == 200
        # mint a guest user + PAT: read-only enforcement
        status, guest = call(
            addr, "POST", "/api/v1/users",
            {"name": "viewer", "password": "pw", "role": "guest"}, token=tok,
        )
        status, pat = call(
            addr, "POST", f"/api/v1/users/{guest['id']}/personal-access-tokens",
            {"name": "ci"}, token=tok,
        )
        assert status == 200 and pat["token"].startswith("dfp_")
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat["token"])
        assert status == 200
        status, _ = call(
            addr, "POST", "/api/v1/scheduler-clusters", {"name": "x"},
            token=pat["token"],
        )
        assert status == 403  # guest is read-only
        # revocation kills the token
        status, _ = call(
            addr, "DELETE",
            f"/api/v1/users/{guest['id']}/personal-access-tokens/{pat['id']}",
            token=tok,
        )
        assert status == 200
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat["token"])
        assert status == 401
        # disabling a user kills their remaining tokens
        status, pat2 = call(
            addr, "POST", f"/api/v1/users/{guest['id']}/personal-access-tokens",
            {"name": "ci2"}, token=tok,
        )
        call(addr, "PATCH", f"/api/v1/users/{guest['id']}", {"state": "disabled"}, token=tok)
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=pat2["token"])
        assert status == 401
    finally:
        server.stop()
        db.close()


# ---------------------------------------------------------------------------
# OAuth sign-in (reference manager/handlers/oauth.go + auth/oauth/)
# ---------------------------------------------------------------------------


@pytest.fixture
def fake_idp():
    """OAuth2 provider fake: token endpoint validating client creds +
    code, userinfo endpoint validating the bearer token."""
    import threading
    import urllib.parse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    seen = {"token_body": None}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            if self.path != "/token":
                self.send_error(404)
                return
            body = dict(
                urllib.parse.parse_qsl(
                    self.rfile.read(int(self.headers["Content-Length"])).decode()
                )
            )
            seen["token_body"] = body
            if (
                body.get("client_id") == "cid"
                and body.get("client_secret") == "csec"
                and body.get("code") == "good-code"
            ):
                payload = json.dumps({"access_token": "at-1", "token_type": "bearer"})
                self.send_response(200)
            else:
                payload = json.dumps({"error": "invalid_grant"})
                self.send_response(200)  # oauth2 errors ride 200+JSON too
            data = payload.encode()
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path != "/userinfo":
                self.send_error(404)
                return
            if self.headers.get("Authorization") != "Bearer at-1":
                self.send_error(401)
                return
            data = json.dumps(
                {"id": 424242, "login": "octo", "email": "octo@example.com"}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield {"base": f"http://127.0.0.1:{httpd.server_port}", "seen": seen}
    httpd.shutdown()
    httpd.server_close()


def _make_provider(addr, base):
    status, body = call(
        addr,
        "POST",
        "/api/v1/oauth",
        {
            "name": "fakehub",
            "client_id": "cid",
            "client_secret": "csec",
            "redirect_url": "http://console.local/callback",
            "auth_url": f"{base}/authorize",
            "token_url": f"{base}/token",
            "userinfo_url": f"{base}/userinfo",
            "scopes": "read:user",
        },
    )
    assert status == 200, body
    return body


def test_oauth_provider_crud_redacts_secret(rest, fake_idp):
    addr = rest["addr"]
    created = _make_provider(addr, fake_idp["base"])
    assert "client_secret" not in created and "token_url" not in created
    status, listed = call(addr, "GET", "/api/v1/oauth", token="guest-tok")
    assert status == 200 and listed[0]["name"] == "fakehub"
    status, got = call(addr, "PATCH", f"/api/v1/oauth/{created['id']}", {"bio": "x"})
    assert status == 200 and got["bio"] == "x"
    # guest cannot write providers
    status, _ = call(addr, "POST", "/api/v1/oauth", {}, token="guest-tok")
    assert status == 403
    status, _ = call(addr, "DELETE", f"/api/v1/oauth/{created['id']}")
    assert status == 200
    status, listed = call(addr, "GET", "/api/v1/oauth")
    assert listed == []


def test_oauth_signin_full_flow(rest, fake_idp):
    """Redirect leg → state round-trip → code exchange → user
    provisioned → session token works against the API."""
    import urllib.parse

    addr = rest["addr"]
    _make_provider(addr, fake_idp["base"])

    # unauthenticated browser hits the signin leg; 302 carries state
    req = urllib.request.Request(f"http://{addr}/api/v1/users/signin/fakehub")

    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    opener = urllib.request.build_opener(NoRedirect)
    try:
        opener.open(req, timeout=5)
        raise AssertionError("expected 302")
    except urllib.error.HTTPError as e:
        assert e.code == 302
        loc = e.headers["Location"]
    q = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(loc).query))
    assert loc.startswith(fake_idp["base"] + "/authorize")
    assert q["client_id"] == "cid" and q["redirect_uri"] == "http://console.local/callback"
    state = q["state"]

    # callback with the provider-issued code
    status, body = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state}",
        token=None,
    )
    assert status == 200, body
    assert body["user"]["name"] == "octo" and body["user"]["role"] == "guest"
    assert fake_idp["seen"]["token_body"]["redirect_uri"] == "http://console.local/callback"

    # the minted session token authenticates read access
    status, _ = call(addr, "GET", "/api/v1/schedulers", token=body["token"])
    assert status == 200

    # tampered/mismatched state is rejected
    status, err = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state[:-4]}AAAA",
        token=None,
    )
    assert status == 403

    # bad code: provider refuses, no session
    status, err = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=bad&state={state}",
        token=None,
    )
    assert status in (401, 500) and "token" not in err


def test_oauth_name_collision_cannot_take_over_local_account(rest, fake_idp):
    """An IdP login equal to an existing local admin's name must NOT
    sign into that account: matching is by (provider, subject), and the
    display name gets uniquified."""
    from dragonfly2_tpu.manager import auth as A

    addr = rest["addr"]
    A.create_user(rest["db"], "octo", "hunter2", role="admin")  # local admin
    _make_provider(addr, fake_idp["base"])
    state = _state_secret_signed(rest, "fakehub")
    status, body = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state}",
        token=None,
    )
    assert status == 200, body
    # NOT the admin account: provisioned under a uniquified name, guest role
    assert body["user"]["name"] != "octo" or body["user"]["role"] == "guest"
    assert body["user"]["role"] == "guest"
    local = rest["db"].query_one("SELECT * FROM users WHERE name = 'octo'")
    assert local["role"] == "admin" and local["oauth_subject"] == ""
    # second sign-in reuses the SAME linked account (stable subject)
    state2 = _state_secret_signed(rest, "fakehub")
    status, body2 = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state2}",
        token=None,
    )
    assert status == 200 and body2["user"]["id"] == body["user"]["id"]


def _state_secret(rest):
    from dragonfly2_tpu.manager import auth

    return auth.state_secret(rest["db"])


def _state_secret_signed(rest, provider):
    from dragonfly2_tpu.manager import auth

    return auth.sign_state(_state_secret(rest), provider)


def test_oauth_state_survives_server_restart(rest, fake_idp, tmp_path):
    """The CSRF state key is DB-persisted: a state minted before a
    manager restart verifies after it."""
    from dragonfly2_tpu.manager import auth

    addr = rest["addr"]
    _make_provider(addr, fake_idp["base"])
    state = _state_secret_signed(rest, "fakehub")
    # a fresh RestServer over the same DB (the "restarted" replica)
    from dragonfly2_tpu.manager.rest import RestServer

    server2 = RestServer(rest["service"], tokens={"admin-tok": "admin"})
    addr2 = server2.start()
    try:
        status, body = call(
            addr2,
            "GET",
            f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state}",
            token=None,
        )
        assert status == 200, body
    finally:
        server2.stop()


def test_oauth_bad_code_is_401_and_duplicate_provider_409(rest, fake_idp):
    addr = rest["addr"]
    _make_provider(addr, fake_idp["base"])
    # provider 400s / refuses the code → clean 401, not a 500
    state = _state_secret_signed(rest, "fakehub")
    status, err = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=bad&state={state}",
        token=None,
    )
    assert status == 401, err
    # duplicate provider name → 409 conflict, not 500
    status, err = call(
        addr,
        "POST",
        "/api/v1/oauth",
        {
            "name": "fakehub", "client_id": "x", "client_secret": "y",
            "auth_url": "http://a", "token_url": "http://t", "userinfo_url": "http://u",
        },
    )
    assert status == 409, err


def test_signin_prefix_does_not_unauthenticate_other_routes(rest):
    """/api/v1/users/signin/... exemption is per-route: a path that
    happens to share the prefix but matches another route still needs
    auth."""
    addr = rest["addr"]
    status, _ = call(
        addr, "GET", "/api/v1/users/signin/personal-access-tokens", token=None
    )
    # either the PAT route demands auth (401) or nothing matches (404);
    # anything but an unauthenticated 200/400 is fine
    assert status in (401, 404)


def test_oauth_refuses_userinfo_without_stable_subject(rest, fake_idp, monkeypatch):
    """login-only userinfo (a reassignable handle) must be refused, not
    used as the account link key."""
    from dragonfly2_tpu.manager import auth

    monkeypatch.setattr(
        auth, "oauth_userinfo", lambda p, t, timeout=10.0: {"login": "octo"}
    )
    addr = rest["addr"]
    _make_provider(addr, fake_idp["base"])
    state = _state_secret_signed(rest, "fakehub")
    status, err = call(
        addr,
        "GET",
        f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state}",
        token=None,
    )
    assert status == 401 and "stable subject" in err["error"]


def test_oauth_guest_does_not_close_admin_bootstrap(tmp_path, fake_idp):
    """Token-less dev mode: an OAuth-provisioned guest must not end the
    anonymous-admin bootstrap window (that would lock every write route
    with no admin in existence); creating an admin user does."""
    from dragonfly2_tpu.manager.rest import RestServer

    db = Database(tmp_path / "boot.db")
    service = ManagerService(db, ModelRegistry(db, FSObjectStorage(tmp_path / "o")))
    server = RestServer(service)  # no config tokens
    addr = server.start()
    try:
        status, _ = _make_provider_status(addr, fake_idp["base"], token=None)
        assert status == 200  # anonymous admin can configure the provider
        from dragonfly2_tpu.manager import auth

        state = auth.sign_state(auth.state_secret(db), "fakehub")
        status, body = call(
            addr,
            "GET",
            f"/api/v1/users/signin/fakehub/callback?code=good-code&state={state}",
            token=None,
        )
        assert status == 200 and body["user"]["role"] == "guest"
        # bootstrap window still open: anonymous can create the admin
        status, admin = call(
            addr, "POST", "/api/v1/users",
            {"name": "root", "password": "pw12345", "role": "admin"}, token=None,
        )
        assert status == 200, admin
        # and NOW anonymous write access is gone
        status, _ = call(
            addr, "POST", "/api/v1/applications", {"name": "x"}, token=None
        )
        assert status == 401
    finally:
        server.stop()
        db.close()


def _make_provider_status(addr, base, token="admin-tok"):
    return call(
        addr,
        "POST",
        "/api/v1/oauth",
        {
            "name": "fakehub", "client_id": "cid", "client_secret": "csec",
            "auth_url": f"{base}/authorize", "token_url": f"{base}/token",
            "userinfo_url": f"{base}/userinfo",
        },
        token=token,
    )


def test_configs_crud(rest):
    addr = rest["addr"]
    status, row = call(addr, "POST", "/api/v1/configs", {"name": "gc-ttl", "value": "3600"})
    assert status == 200 and row["value"] == "3600"
    status, got = call(addr, "GET", "/api/v1/configs/gc-ttl", token="guest-tok")
    assert status == 200 and got["id"] == row["id"]
    status, upd = call(addr, "PATCH", f"/api/v1/configs/{row['id']}", {"value": "60"})
    assert status == 200 and upd["value"] == "60"
    status, _ = call(addr, "POST", "/api/v1/configs", {"name": "gc-ttl"})
    assert status == 409  # UNIQUE name
    status, _ = call(addr, "DELETE", f"/api/v1/configs/{row['id']}")
    assert status == 200
    status, listed = call(addr, "GET", "/api/v1/configs")
    assert listed == []


def test_buckets_crud(rest):
    addr = rest["addr"]
    status, listed = call(addr, "GET", "/api/v1/buckets")
    assert status == 200  # models bucket pre-created by the registry
    before = {b["name"] for b in listed}
    status, made = call(addr, "POST", "/api/v1/buckets", {"name": "blobs"})
    assert status == 200
    status, got = call(addr, "GET", "/api/v1/buckets/blobs")
    assert status == 200 and got["objects"] == 0
    status, listed = call(addr, "GET", "/api/v1/buckets")
    assert {b["name"] for b in listed} == before | {"blobs"}
    status, _ = call(addr, "GET", "/api/v1/buckets/nope")
    assert status == 404
    status, _ = call(addr, "POST", "/api/v1/buckets", {"name": "../escape"})
    assert status == 400
    status, _ = call(addr, "DELETE", "/api/v1/buckets/blobs")
    assert status == 200


def test_peers_materialized_from_sync_peers_job(rest):
    """sync_peers job result → peers rows the REST surface serves
    (reference handlers/peer.go backed by the sync-peers job)."""
    import grpc as _grpc

    from dragonfly2_tpu.rpc import glue
    import manager_pb2

    service = rest["service"]
    server, port = glue.serve({"dragonfly2_tpu.manager.Manager": service})
    try:
        chan = glue.dial(f"127.0.0.1:{port}")
        client = glue.ServiceClient(chan, "dragonfly2_tpu.manager.Manager")
        job = client.CreateJob(
            manager_pb2.CreateJobRequest(
                type="sync_peers", args_json="{}", scheduler_cluster_id=1
            )
        )
        leased = client.ListPendingJobs(
            manager_pb2.ListPendingJobsRequest(
                ip="10.9.9.9", hostname="sched-w", scheduler_cluster_id=1
            )
        )
        assert [j.id for j in leased.jobs] == [job.id]
        result = json.dumps(
            {
                "hosts": [
                    {"id": "h-1", "hostname": "a", "ip": "10.0.0.1",
                     "type": "normal", "peer_count": 3, "upload_count": 7},
                    {"id": "h-2", "hostname": "b", "ip": "10.0.0.2",
                     "type": "super", "peer_count": 1, "upload_count": 0},
                ]
            }
        )
        client.UpdateJobResult(
            manager_pb2.UpdateJobResultRequest(
                id=job.id, state="succeeded", result_json=result,
                ip="10.9.9.9", hostname="sched-w",
            )
        )
        chan.close()
    finally:
        server.stop(0)

    status, peers = call(rest["addr"], "GET", "/api/v1/peers?scheduler_cluster_id=1")
    assert status == 200 and len(peers) == 2
    by_host = {p["host_id"]: p for p in peers}
    assert by_host["h-1"]["peer_count"] == 3 and by_host["h-2"]["type"] == "super"
    status, one = call(rest["addr"], "GET", f"/api/v1/peers/{peers[0]['id']}")
    assert status == 200 and one["host_id"] in by_host
    status, _ = call(rest["addr"], "DELETE", f"/api/v1/peers/{peers[0]['id']}")
    assert status == 200
    status, remaining = call(rest["addr"], "GET", "/api/v1/peers")
    assert len(remaining) == 1


def test_config_numeric_name_never_shadows_id(rest):
    addr = rest["addr"]
    _, a = call(addr, "POST", "/api/v1/configs", {"name": "2", "value": "A"})
    _, b = call(addr, "POST", "/api/v1/configs", {"name": "gc", "value": "B"})
    assert a["id"] == 1 and b["id"] == 2
    # id lookup resolves config B, not config A (whose NAME is "2")
    status, got = call(addr, "GET", "/api/v1/configs/2")
    assert got["name"] == "gc"
    status, _ = call(addr, "DELETE", "/api/v1/configs/2")
    status, remaining = call(addr, "GET", "/api/v1/configs")
    assert [r["name"] for r in remaining] == ["2"]
    # malformed bodies are client errors, not 500s
    status, _ = call(addr, "POST", "/api/v1/configs", {"name": 7})
    assert status == 400
    status, _ = call(addr, "PATCH", "/api/v1/configs/1", {"name": ""})
    assert status == 400
    status, _ = call(addr, "POST", "/api/v1/buckets", {"name": 5})
    assert status == 400
    # structured config values stored as JSON
    status, c = call(addr, "POST", "/api/v1/configs", {"name": "j", "value": {"a": 1}})
    assert status == 200 and json.loads(c["value"]) == {"a": 1}


def test_malformed_sync_peers_result_leaves_peers_intact(rest):
    """A worker-supplied result with bad row shapes must neither wipe
    the peers table nor fail the RPC (the job row already committed)."""
    from dragonfly2_tpu.rpc import glue
    import manager_pb2

    service = rest["service"]
    server, port = glue.serve({"dragonfly2_tpu.manager.Manager": service})
    try:
        chan = glue.dial(f"127.0.0.1:{port}")
        client = glue.ServiceClient(chan, "dragonfly2_tpu.manager.Manager")

        def run_job(result_json):
            job = client.CreateJob(manager_pb2.CreateJobRequest(
                type="sync_peers", args_json="{}", scheduler_cluster_id=1))
            client.ListPendingJobs(manager_pb2.ListPendingJobsRequest(
                ip="1.1.1.1", hostname="w", scheduler_cluster_id=1))
            return client.UpdateJobResult(manager_pb2.UpdateJobResultRequest(
                id=job.id, state="succeeded", result_json=result_json,
                ip="1.1.1.1", hostname="w"))

        run_job(json.dumps({"hosts": [{"id": "keep", "peer_count": 1}]}))
        status, peers = call(rest["addr"], "GET", "/api/v1/peers")
        assert [p["host_id"] for p in peers] == ["keep"]
        # null count coerces to 0 — a usable row, refresh applies
        r = run_job(json.dumps({"hosts": [{"id": "nul", "peer_count": None}]}))
        assert r.state == "succeeded"
        status, peers = call(rest["addr"], "GET", "/api/v1/peers")
        assert [(p["host_id"], p["peer_count"]) for p in peers] == [("nul", 0)]
        # truly unusable rows (non-numeric count) → logged no-op, RPC succeeds
        r = run_job(json.dumps({"hosts": [{"id": "bad", "peer_count": "NaNsense"}]}))
        assert r.state == "succeeded"
        # list-shaped result (valid JSON, wrong shape) → also a no-op
        r = run_job("[]")
        assert r.state == "succeeded"
        status, peers = call(rest["addr"], "GET", "/api/v1/peers")
        assert [p["host_id"] for p in peers] == ["nul"]
        chan.close()
    finally:
        server.stop(0)


def test_openapi_spec_matches_route_table(rest):
    """The live-derived OpenAPI document covers every registered route
    with correct method, params, and auth annotations."""
    status, spec = call(rest["addr"], "GET", "/api/v1/openapi.json", token=None)
    assert status == 200 and spec["openapi"].startswith("3.")
    paths = spec["paths"]
    # spot checks across surfaces
    assert "get" in paths["/api/v1/schedulers"]
    assert "put" in paths["/api/v1/models/{model_id}/versions/{version}/state"]
    assert {p["name"] for p in paths["/api/v1/models/{model_id}/versions/{version}"]["get"]["parameters"]} == {"model_id", "version"}
    # auth annotations: signin legs open, writes admin-gated
    assert "security" not in paths["/api/v1/users/signin/{name}"]["get"]
    assert paths["/api/v1/oauth"]["post"]["responses"].get("403")
    # completeness: every registered route appears — derived straight
    # from the route table (independent of how the implementation finds
    # patterns, so a silently skipped route fails here)
    import re as _re

    from dragonfly2_tpu.manager.rest import _ROUTES

    want = {
        (_re.sub(r":(\w+)", r"{\1}", entry[5]), entry[0].lower())
        for entry in _ROUTES
    }
    have = {(p, m) for p, ops in paths.items() for m in ops}
    assert want == have and len(want) >= 45


def test_route_literals_are_escaped(rest):
    """A '.' in a route pattern matches only itself — openapiXjson must
    not resolve the openapi.json route."""
    status, _ = call(rest["addr"], "GET", "/api/v1/openapiXjson", token=None)
    assert status in (401, 404)


def test_group_jobs_fan_out_and_aggregate(rest):
    """scheduler_cluster_ids fans one job to N clusters under a group id
    whose state aggregates machinery-style: any failed → failed, all
    succeeded → succeeded (reference manager/job createGroupJob)."""
    from dragonfly2_tpu.rpc import glue
    import manager_pb2

    addr = rest["addr"]
    status, group = call(
        addr, "POST", "/api/v1/jobs",
        {"type": "preheat", "args": {"url": "https://x/y"},
         "scheduler_cluster_ids": [1, 2]},
    )
    assert status == 200 and len(group["jobs"]) == 2 and group["group_id"]
    gid = group["group_id"]
    status, agg = call(addr, "GET", f"/api/v1/jobs/groups/{gid}")
    assert agg["state"] == "queued"

    service = rest["service"]
    server, port = glue.serve({"dragonfly2_tpu.manager.Manager": service})
    try:
        chan = glue.dial(f"127.0.0.1:{port}")
        client = glue.ServiceClient(chan, "dragonfly2_tpu.manager.Manager")

        def work(cluster, state):
            leased = client.ListPendingJobs(
                manager_pb2.ListPendingJobsRequest(
                    ip="1.1.1.1", hostname=f"w{cluster}",
                    scheduler_cluster_id=cluster,
                )
            )
            assert len(leased.jobs) == 1
            client.UpdateJobResult(
                manager_pb2.UpdateJobResultRequest(
                    id=leased.jobs[0].id, state=state, result_json="{}",
                    ip="1.1.1.1", hostname=f"w{cluster}",
                )
            )

        work(1, "succeeded")
        status, agg = call(addr, "GET", f"/api/v1/jobs/groups/{gid}")
        assert agg["state"] == "queued"  # one member still pending
        work(2, "succeeded")
        status, agg = call(addr, "GET", f"/api/v1/jobs/groups/{gid}")
        assert agg["state"] == "succeeded"
        chan.close()
    finally:
        server.stop(0)

    # single-cluster create keeps the old shape (no group wrapper)
    status, single = call(
        addr, "POST", "/api/v1/jobs",
        {"type": "preheat", "args": {}, "scheduler_cluster_id": 1},
    )
    assert status == 200 and "id" in single and single.get("group_id") == ""

    # a failed member fails the whole group
    status, g2 = call(
        addr, "POST", "/api/v1/jobs",
        {"type": "preheat", "args": {}, "scheduler_cluster_ids": [3, 4]},
    )
    rest["db"].execute(
        "UPDATE jobs SET state = 'failed' WHERE id = ?", (g2["jobs"][0]["id"],)
    )
    status, agg = call(addr, "GET", f"/api/v1/jobs/groups/{g2['group_id']}")
    assert agg["state"] == "failed"
    status, _ = call(addr, "GET", "/api/v1/jobs/groups/nope")
    assert status == 404


def test_group_job_validation_and_single_element_list(rest):
    addr = rest["addr"]
    # invalid id anywhere → 400, and NO orphaned rows inserted
    status, err = call(
        addr, "POST", "/api/v1/jobs",
        {"type": "preheat", "scheduler_cluster_ids": [1, "abc"]},
    )
    assert status == 400
    status, jobs = call(addr, "GET", "/api/v1/jobs")
    assert jobs == []
    # a 1-element LIST still follows the group contract
    status, g = call(
        addr, "POST", "/api/v1/jobs",
        {"type": "preheat", "scheduler_cluster_ids": [7]},
    )
    assert status == 200 and g["group_id"] and len(g["jobs"]) == 1
    status, agg = call(addr, "GET", f"/api/v1/jobs/groups/{g['group_id']}")
    assert status == 200 and agg["state"] == "queued"


def test_rest_job_defaults_to_default_cluster(rest):
    """A REST-created job without scheduler_cluster_id must land in the
    DEFAULT cluster, not dead-letter in cluster 0 no worker leases."""
    status, job = call(rest["addr"], "POST", "/api/v1/jobs", {"type": "preheat"})
    assert status == 200
    assert job["scheduler_cluster_id"] == rest["service"].default_cluster_id != 0


def test_signin_ttl_zero_is_capped(rest):
    from dragonfly2_tpu.manager import auth

    auth.create_user(rest["db"], "sess", "pw12345", role="guest")
    status, out = call(
        rest["addr"], "POST", "/api/v1/users/signin",
        {"name": "sess", "password": "pw12345", "ttl": 0}, token=None,
    )
    assert status == 200
    row = rest["db"].query_one(
        "SELECT expires_at FROM personal_access_tokens ORDER BY id DESC LIMIT 1"
    )
    assert row["expires_at"] > 0  # never-expiring stays admin-route-only


def test_oauth_numeric_name_never_shadows_provider_id(rest, fake_idp):
    a = _make_provider(addr := rest["addr"], fake_idp["base"])
    status, b = call(
        addr, "POST", "/api/v1/oauth",
        {"name": str(a["id"]), "client_id": "x", "client_secret": "y",
         "auth_url": "http://a", "token_url": "http://t", "userinfo_url": "http://u"},
    )
    assert status == 200
    status, got = call(addr, "GET", f"/api/v1/oauth/{a['id']}")
    assert got["name"] == a["name"]  # id lookup resolves A, never B
    status, _ = call(addr, "DELETE", f"/api/v1/oauth/{a['id']}")
    status, listed = call(addr, "GET", "/api/v1/oauth")
    assert [r["name"] for r in listed] == [str(a["id"])]


class TestUserLifecycle:
    """Round-5 REST completion (reference router.go:97-111): signup,
    signout, refresh_token, reset_password."""

    def test_signup_is_guest_only(self, rest):
        addr = rest["addr"]
        status, user = call(
            addr, "POST", "/api/v1/users/signup",
            {"name": "joiner", "password": "pw1", "role": "admin"},  # role ignored
            token=None,  # unauthenticated route
        )
        assert status == 200 and user["role"] == "guest"
        assert "password_hash" not in user and "password_salt" not in user

    def test_signout_revokes_session(self, rest):
        addr = rest["addr"]
        call(addr, "POST", "/api/v1/users",
             {"name": "op", "password": "pw", "role": "admin"})
        _, session = call(addr, "POST", "/api/v1/users/signin",
                          {"name": "op", "password": "pw"}, token=None)
        tok = session["token"]
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=tok)
        assert status == 200
        status, out = call(addr, "POST", "/api/v1/users/signout", {}, token=tok)
        assert status == 200 and out["signed_out"]
        status, _ = call(addr, "GET", "/api/v1/schedulers", token=tok)
        assert status == 401  # the token died with the session
        # config-file tokens aren't revocable sessions
        status, _ = call(addr, "POST", "/api/v1/users/signout", {}, token="admin-tok")
        assert status == 400

    def test_refresh_token_rotates(self, rest):
        addr = rest["addr"]
        call(addr, "POST", "/api/v1/users",
             {"name": "op2", "password": "pw", "role": "admin"})
        _, session = call(addr, "POST", "/api/v1/users/signin",
                          {"name": "op2", "password": "pw"}, token=None)
        old = session["token"]
        status, out = call(addr, "POST", "/api/v1/users/refresh_token", {}, token=old)
        assert status == 200 and out["token"] and out["token"] != old
        # new works, old is revoked
        assert call(addr, "GET", "/api/v1/schedulers", token=out["token"])[0] == 200
        assert call(addr, "GET", "/api/v1/schedulers", token=old)[0] == 401

    def test_reset_password_requires_old(self, rest):
        addr = rest["addr"]
        _, user = call(addr, "POST", "/api/v1/users",
                       {"name": "r", "password": "old-pw", "role": "guest"})
        status, _ = call(
            addr, "POST", f"/api/v1/users/{user['id']}/reset_password",
            {"old_password": "WRONG", "new_password": "new-pw"}, token=None,
        )
        assert status == 401
        status, out = call(
            addr, "POST", f"/api/v1/users/{user['id']}/reset_password",
            {"old_password": "old-pw", "new_password": "new-pw"}, token=None,
        )
        assert status == 200
        # old password dead, new one signs in
        assert call(addr, "POST", "/api/v1/users/signin",
                    {"name": "r", "password": "old-pw"}, token=None)[0] == 401
        assert call(addr, "POST", "/api/v1/users/signin",
                    {"name": "r", "password": "new-pw"}, token=None)[0] == 200


class TestRolesSurface:
    def test_roles_and_permissions_read(self, rest):
        addr = rest["addr"]
        status, roles = call(addr, "GET", "/api/v1/roles", token="guest-tok")
        assert status == 200 and set(roles) == {"admin", "guest"}
        status, role = call(addr, "GET", "/api/v1/roles/guest", token="guest-tok")
        assert status == 200
        actions = {p["action"] for p in role["permissions"]}
        assert "GET" in actions and "DELETE" not in actions  # guest is read-only
        status, admin_role = call(addr, "GET", "/api/v1/roles/admin")
        assert {p["action"] for p in admin_role["permissions"]} >= {"GET", "POST", "DELETE"}
        status, perms = call(addr, "GET", "/api/v1/permissions")
        assert status == 200 and len(perms) > 40
        assert call(addr, "GET", "/api/v1/roles/root")[0] == 404

    def test_user_role_assignment(self, rest):
        addr = rest["addr"]
        _, user = call(addr, "POST", "/api/v1/users",
                       {"name": "promote-me", "password": "pw"})
        assert call(addr, "GET", f"/api/v1/users/{user['id']}/roles")[1] == ["guest"]
        status, out = call(addr, "PUT", f"/api/v1/users/{user['id']}/roles/admin", {})
        assert status == 200 and out["role"] == "admin"
        status, out = call(addr, "DELETE", f"/api/v1/users/{user['id']}/roles/admin")
        assert status == 200 and out["role"] == "guest"
        assert call(addr, "DELETE", f"/api/v1/users/{user['id']}/roles/admin")[0] == 404


class TestSeedPeerClusters:
    def test_crud_and_assignment(self, rest):
        addr = rest["addr"]
        status, c = call(addr, "POST", "/api/v1/seed-peer-clusters",
                         {"name": "spc-1", "config": {"load_limit": 100}})
        assert status == 200 and c["name"] == "spc-1"
        status, rows = call(addr, "GET", "/api/v1/seed-peer-clusters", token="guest-tok")
        assert status == 200 and len(rows) == 1
        status, c2 = call(addr, "PATCH", f"/api/v1/seed-peer-clusters/{c['id']}",
                          {"config": {"load_limit": 50}})
        assert status == 200 and json.loads(c2["config"]) == {"load_limit": 50}
        # move a registered seed peer into the new cluster
        import time as _time

        rest["db"].execute(
            "INSERT INTO seed_peers (hostname, ip, port, seed_peer_cluster_id,"
            " created_at, updated_at) VALUES ('sp-h', '10.0.0.9', 1, 999, ?, ?)",
            (_time.time(), _time.time()),
        )
        sp = rest["db"].query_one("SELECT id FROM seed_peers WHERE hostname='sp-h'")
        status, out = call(
            addr, "PUT", f"/api/v1/seed-peer-clusters/{c['id']}/seed-peers/{sp['id']}", {}
        )
        assert status == 200
        moved = rest["db"].query_one(
            "SELECT seed_peer_cluster_id FROM seed_peers WHERE id = ?", (sp["id"],)
        )
        assert moved["seed_peer_cluster_id"] == c["id"]
        status, _ = call(addr, "DELETE", f"/api/v1/seed-peer-clusters/{c['id']}")
        assert status == 200
        assert call(addr, "GET", f"/api/v1/seed-peer-clusters/{c['id']}")[0] == 404


class TestApplicationsFullCrud:
    def test_get_patch_delete(self, rest):
        addr = rest["addr"]
        _, app = call(addr, "POST", "/api/v1/applications",
                      {"name": "ml-sync", "url": "https://repo", "priority": {"value": 5}})
        status, got = call(addr, "GET", f"/api/v1/applications/{app['id']}",
                           token="guest-tok")
        assert status == 200 and got["name"] == "ml-sync"
        status, upd = call(addr, "PATCH", f"/api/v1/applications/{app['id']}",
                           {"url": "https://repo2"})
        assert status == 200 and upd["url"] == "https://repo2"
        status, _ = call(addr, "DELETE", f"/api/v1/applications/{app['id']}")
        assert status == 200
        assert call(addr, "GET", f"/api/v1/applications/{app['id']}")[0] == 404
        assert call(addr, "PATCH", "/api/v1/applications/424242", {"url": "x"})[0] == 404


class TestPatOpenApi:
    def test_toplevel_pat_crud_and_oapi_access(self, rest):
        addr = rest["addr"]
        _, user = call(addr, "POST", "/api/v1/users",
                       {"name": "automation", "password": "pw", "role": "admin"})
        status, pat = call(addr, "POST", "/api/v1/personal-access-tokens",
                           {"user_id": user["id"], "name": "ci"})
        assert status == 200 and pat["token"]
        status, rows = call(addr, "GET", "/api/v1/personal-access-tokens")
        assert status == 200 and any(r["id"] == pat["id"] for r in rows)
        status, one = call(addr, "GET", f"/api/v1/personal-access-tokens/{pat['id']}")
        assert status == 200 and one["name"] == "ci"

        # the open API surface: a PAT drives jobs + clusters CRUD
        tok = pat["token"]
        status, c = call(addr, "POST", "/oapi/v1/clusters",
                         {"name": "oapi-c"}, token=tok)
        assert status == 200
        status, rows = call(addr, "GET", "/oapi/v1/clusters", token=tok)
        assert status == 200 and any(r["name"] == "oapi-c" for r in rows)
        assert call(addr, "GET", "/oapi/v1/jobs", token=tok)[0] == 200

        # deactivate, then the PAT stops working; reactivate restores
        status, _ = call(addr, "PATCH", f"/api/v1/personal-access-tokens/{pat['id']}",
                         {"state": "inactive"})
        assert status == 200
        assert call(addr, "GET", "/oapi/v1/clusters", token=tok)[0] == 401
        call(addr, "PATCH", f"/api/v1/personal-access-tokens/{pat['id']}",
             {"state": "active"})
        assert call(addr, "GET", "/oapi/v1/clusters", token=tok)[0] == 200
        # revoke is terminal
        call(addr, "DELETE", f"/api/v1/personal-access-tokens/{pat['id']}")
        assert call(addr, "GET", "/oapi/v1/clusters", token=tok)[0] == 401


def test_route_census():
    """Executable census (docs/manager-api.md): re-derive the reference's
    route table from router.go and assert the ONLY rows we don't serve
    verbatim are the documented deltas. Skips when the reference tree
    isn't present (the doc table stays the human-readable record)."""
    import os
    import re as _re

    router = "/root/reference/manager/router/router.go"
    if not os.path.exists(router):
        pytest.skip("reference tree not available")
    from dragonfly2_tpu.manager.rest import _ROUTES

    prefix = {
        "u": "/api/v1/users", "re": "/api/v1/roles", "pm": "/api/v1/permissions",
        "oa": "/api/v1/oauth", "c": "/api/v1/clusters",
        "sc": "/api/v1/scheduler-clusters", "s": "/api/v1/schedulers",
        "spc": "/api/v1/seed-peer-clusters", "sp": "/api/v1/seed-peers",
        "peer": "/api/v1/peers", "bucket": "/api/v1/buckets",
        "config": "/api/v1/configs", "job": "/api/v1/jobs",
        "cs": "/api/v1/applications", "model": "/api/v1/models",
        "pat": "/api/v1/personal-access-tokens", "ojob": "/oapi/v1/jobs",
        "oc": "/oapi/v1/clusters", "pv1": "/preheats",
    }
    ref = set()
    for line in open(router):
        m = _re.match(r'\s*(\w+)\.(GET|POST|PATCH|DELETE|PUT)\("([^"]*)"', line)
        if m:
            g, meth, path = m.groups()
            base = prefix.get(g, "" if g == "r" else None)
            if base is None:
                continue
            ref.add((meth, (base + ("/" + path if path else "")).replace("//", "/")))
    ours = {(m, p) for m, _r, _f, _w, _a, p in _ROUTES}
    documented_deltas = {
        ("GET", "/api/v1/buckets/:id"),
        ("DELETE", "/api/v1/buckets/:id"),
        ("GET", "/api/v1/models/:id"),
        ("PATCH", "/api/v1/models/:id"),
        ("DELETE", "/api/v1/models/:id"),
        ("POST", "/api/v1/roles"),
        ("DELETE", "/api/v1/roles/:role"),
        ("POST", "/api/v1/roles/:role/permissions"),
        ("DELETE", "/api/v1/roles/:role/permissions"),
        ("PUT", "/api/v1/seed-peer-clusters/:id/scheduler-clusters/:scheduler_cluster_id"),
        ("GET", "/swagger/*any"),
    }
    missing = {r for r in ref if r not in ours}
    undocumented = missing - documented_deltas
    assert not undocumented, f"reference routes neither served nor documented: {sorted(undocumented)}"
    stale = documented_deltas - missing
    assert not stale, f"documented deltas that now exist (update the doc): {sorted(stale)}"


def test_composite_clusters_and_v1_preheat(rest):
    """Reference /api/v1/clusters (one resource = scheduler + seed-peer
    cluster pair, router.go:133-139) and the v1-compat /preheats alias."""
    addr = rest["addr"]
    status, c = call(addr, "POST", "/api/v1/clusters",
                     {"name": "site-a", "is_default": True,
                      "seed_peer_cluster_config": {"load_limit": 3}})
    assert status == 200
    assert c["scheduler_cluster"]["name"] == c["seed_peer_cluster"]["name"] == "site-a"
    status, rows = call(addr, "GET", "/api/v1/clusters", token="guest-tok")
    mine = next(r for r in rows if r["name"] == "site-a")  # DB pre-seeds 'default'
    assert status == 200 and mine["seed_peer_cluster"] is not None
    status, got = call(addr, "GET", f"/api/v1/clusters/{c['id']}")
    assert status == 200 and got["seed_peer_cluster"]["name"] == "site-a"
    status, upd = call(addr, "PATCH", f"/api/v1/clusters/{c['id']}",
                       {"config": {"x": 1}, "seed_peer_cluster_config": {"y": 2}})
    assert status == 200
    assert json.loads(upd["scheduler_cluster"]["config"]) == {"x": 1}
    assert json.loads(upd["seed_peer_cluster"]["config"]) == {"y": 2}
    status, _ = call(addr, "DELETE", f"/api/v1/clusters/{c['id']}")
    assert status == 200
    assert call(addr, "GET", "/api/v1/seed-peer-clusters", token="guest-tok")[1] == []

    # v1 preheat compat: POST /preheats -> a queued preheat job
    status, ph = call(addr, "POST", "/preheats", {"url": "https://x/blob"})
    assert status == 200 and ph["status"] == "queued"
    status, got = call(addr, "GET", f"/preheats/{ph['id']}")
    assert status == 200 and got["status"] in ("queued", "running")
    assert call(addr, "GET", "/_ping", token=None)[0] == 200


def test_pat_metadata_restricted_to_admin_or_owner(tmp_path):
    """Token metadata is a credential inventory (ISSUE r6): the
    top-level PAT routes are admin-only, and the per-user list is
    readable only by an admin or the user it belongs to."""
    db = Database(tmp_path / "pat.db")
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "obj"))
    server = RestServer(ManagerService(db, models))
    addr = server.start()
    try:
        # bootstrap an admin + two guests, each with a PAT
        status, _ = call(
            addr, "POST", "/api/v1/users",
            {"name": "root", "password": "pw", "role": "admin"}, token=None,
        )
        assert status == 200
        status, session = call(
            addr, "POST", "/api/v1/users/signin",
            {"name": "root", "password": "pw"}, token=None,
        )
        admin_tok = session["token"]
        users, toks = {}, {}
        for name in ("alice", "bob"):
            status, u = call(
                addr, "POST", "/api/v1/users",
                {"name": name, "password": "pw", "role": "guest"}, token=admin_tok,
            )
            assert status == 200
            users[name] = u["id"]
            status, pat = call(
                addr, "POST", f"/api/v1/users/{u['id']}/personal-access-tokens",
                {"name": f"{name}-tok"}, token=admin_tok,
            )
            assert status == 200
            toks[name] = pat["token"]

        # top-level inventory: admin yes, guest no
        status, body = call(addr, "GET", "/api/v1/personal-access-tokens", token=admin_tok)
        assert status == 200 and len(body) >= 3
        status, _ = call(addr, "GET", "/api/v1/personal-access-tokens", token=toks["alice"])
        assert status == 403
        # single token: admin yes; guests can't read others' (or even
        # probe ids — 403, not 404)
        some_id = body[0]["id"]
        status, row = call(
            addr, "GET", f"/api/v1/personal-access-tokens/{some_id}", token=admin_tok
        )
        assert status == 200 and "token_hash" not in row
        status, _ = call(
            addr, "GET", f"/api/v1/personal-access-tokens/{some_id}", token=toks["alice"]
        )
        assert status == 403
        status, _ = call(
            addr, "GET", "/api/v1/personal-access-tokens/999999", token=toks["alice"]
        )
        assert status == 403  # non-existent id leaks nothing to guests

        # per-user list: owner yes, other guest no, admin yes
        status, mine = call(
            addr, "GET", f"/api/v1/users/{users['alice']}/personal-access-tokens",
            token=toks["alice"],
        )
        assert status == 200 and all(r["user_id"] == users["alice"] for r in mine)
        status, _ = call(
            addr, "GET", f"/api/v1/users/{users['alice']}/personal-access-tokens",
            token=toks["bob"],
        )
        assert status == 403
        status, _ = call(
            addr, "GET", f"/api/v1/users/{users['alice']}/personal-access-tokens",
            token=admin_tok,
        )
        assert status == 200
    finally:
        server.stop()
        db.close()
