"""TPU-resident topology engine: device sparse adjacency fed by the
probe plane through the batching delta queue, landmark RTT inference
for unprobed pairs, staleness decay, and the consumer wiring
(NetworkTopology mirror, MLEvaluator rtt feature, seed placement,
query RPC)."""

import numpy as np
import pytest

from dragonfly2_tpu.scheduler.networktopology import NetworkTopology, Probe
from dragonfly2_tpu.scheduler.resource import Host, HostManager
from dragonfly2_tpu.topology import TopologyConfig, TopologyEngine
from dragonfly2_tpu.topology.csr import AdjacencyStore
from dragonfly2_tpu.topology.kernels import INF_MS, JaxKernels, NumpyKernels
from dragonfly2_tpu.utils.kvstore import KVStore

MS = 1_000_000  # ns per ms


def make_engine(**kw) -> TopologyEngine:
    kw.setdefault("backend", "numpy")  # the no-accelerator fallback path
    kw.setdefault("flush_threshold", 10**9)  # explicit flushes only
    kw.setdefault("num_landmarks", 4)
    return TopologyEngine(TopologyConfig(**kw))


def feed_star(eng: TopologyEngine, spokes: int = 5, at: float = 1000.0) -> None:
    """Hub topology: hub↔spoke probed, spokes never probed pairwise."""
    for i in range(1, spokes + 1):
        eng.enqueue("h0", f"h{i}", rtt_ns=5 * i * MS, created_at=at)
        eng.enqueue(f"h{i}", "h0", rtt_ns=5 * i * MS, created_at=at)


class TestDeltaQueueAndCSR:
    def test_incremental_flushes_equal_from_scratch_rebuild(self):
        """Many small delta flushes must land on the same adjacency as
        one from-scratch build over the same probe sequence."""
        rng = np.random.default_rng(0)
        probes = []
        for i in range(300):
            s, d = rng.integers(0, 12, size=2)
            if s != d:
                probes.append(
                    (f"h{s}", f"h{d}", int(rng.integers(1, 50)) * MS, 1000.0 + i)
                )

        incremental = make_engine()
        for i, (s, d, r, t) in enumerate(probes):
            incremental.enqueue(s, d, r, t)
            if i % 7 == 0:
                incremental.flush(now=2000.0)
        incremental.flush(now=2000.0)

        scratch = AdjacencyStore()
        for s, d, r, t in probes:
            scratch.apply_probe(s, d, r, t)

        assert incremental.store.index == scratch.index
        assert set(incremental.store.edges) == set(scratch.edges)
        for k, v in scratch.edges.items():
            assert incremental.store.edges[k][0] == pytest.approx(v[0])

        # the built CSR arrays agree too (same capacity policy)
        a = incremental.store.build_arrays(2000.0)
        b = scratch.build_arrays(2000.0)
        e = a["num_edges"]
        assert e == b["num_edges"]
        np.testing.assert_array_equal(a["edge_src"][:e], b["edge_src"][:e])
        np.testing.assert_array_equal(a["edge_dst"][:e], b["edge_dst"][:e])
        np.testing.assert_allclose(a["rtt_log_ms"][:e], b["rtt_log_ms"][:e])

    def test_csr_row_ptr_indexes_out_edges(self):
        eng = make_engine()
        feed_star(eng)
        eng.flush(now=1000.0)
        arr = eng.store.build_arrays(1000.0)
        idx = eng.store.index["h0"]
        lo, hi = int(arr["row_ptr"][idx]), int(arr["row_ptr"][idx + 1])
        assert hi - lo == 5  # hub has 5 out-edges
        np.testing.assert_array_equal(arr["edge_src"][lo:hi], idx)

    def test_ewma_matches_kv_path(self):
        """The engine's per-edge EWMA fold must agree with the KV
        store's int-arithmetic fold exactly."""
        hm = HostManager()
        for i in range(2):
            hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip="10.0.0.1", port=1))
        eng = make_engine()
        nt = NetworkTopology(KVStore(), hm, None, engine=eng)
        for rtt in (10 * MS, 20 * MS, 7 * MS, 33 * MS):
            nt.enqueue_probe("h0", Probe("h1", rtt_ns=rtt))
        eng.flush()
        s, d = eng.store.index["h0"], eng.store.index["h1"]
        assert int(eng.store.edges[(s, d)][0]) == nt.average_rtt("h0", "h1")

    def test_queue_cap_drops_oldest(self):
        import time

        eng = make_engine(max_pending=10)
        now = time.time()
        for i in range(25):
            eng.enqueue("a", "b", rtt_ns=(i + 1) * MS, created_at=now + i)
        assert len(eng.deltas) == 10
        assert eng.deltas.dropped == 15
        eng.flush()
        # the newest sample dominates the EWMA — the drops lost nothing
        # a later probe wouldn't have overwritten anyway
        assert eng.stats()["edges"] == 1


class TestLandmarkInference:
    def test_unprobed_pair_gets_finite_estimate(self):
        eng = make_engine()
        feed_star(eng)
        eng.flush(now=1000.0)
        est = eng.est_rtt_ns("h1", "h2")
        assert est is not None and np.isfinite(est)
        # min-plus through the hub: 5ms + 10ms
        assert est == pytest.approx(15 * MS, rel=0.01)

    def test_symmetric_probe_agreement(self):
        """Inference must not depend on query order for unprobed pairs."""
        eng = make_engine()
        feed_star(eng)
        eng.flush(now=1000.0)
        assert eng.est_rtt_ns("h2", "h4") == eng.est_rtt_ns("h4", "h2")

    def test_triangle_bound(self):
        """est_rtt(a,b) ≤ d(a,l) + d(l,b) for every landmark l — the
        estimate is a min over landmark paths, so no single path can
        beat it."""
        eng = make_engine()
        rng = np.random.default_rng(1)
        hosts = [f"h{i}" for i in range(8)]
        direct = {}
        for s in hosts:
            for d in hosts:
                if s < d and rng.random() < 0.5:
                    rtt = int(rng.integers(2, 40)) * MS
                    eng.enqueue(s, d, rtt, created_at=1000.0)
                    direct[(s, d)] = rtt
        eng.flush(now=1000.0)
        D = np.asarray(eng._D)
        for a in hosts:
            for b in hosts:
                if a == b:
                    continue
                ia, ib = eng.store.index[a], eng.store.index[b]
                if (ia, ib) in eng.store.edges or (ib, ia) in eng.store.edges:
                    continue  # direct EWMA wins by design; the bound is on inference
                est = eng.est_rtt_ns(a, b)
                if est is None:
                    continue
                per_landmark = D[ia] + D[ib]
                finite = per_landmark[per_landmark < INF_MS / 2]
                if len(finite):
                    assert est / MS <= finite.min() * 1.001

    def test_direct_edge_wins_over_inference(self):
        eng = make_engine()
        feed_star(eng)
        # h1↔h2 also probed directly, much slower than the hub path
        eng.enqueue("h1", "h2", rtt_ns=200 * MS, created_at=1000.0)
        eng.flush(now=1000.0)
        assert eng.est_rtt_ns("h1", "h2") == 200 * MS

    def test_disconnected_pair_is_none(self):
        eng = make_engine()
        feed_star(eng, spokes=2)
        eng.enqueue("island-a", "island-b", rtt_ns=3 * MS, created_at=1000.0)
        eng.flush(now=1000.0)
        assert eng.est_rtt_ns("h1", "island-a") is None
        assert eng.est_rtt_ns("h1", "no-such-host") is None

    def test_jax_and_numpy_backends_agree(self):
        """The jitted path and the fallback are one contract."""
        engines = {}
        for backend in ("numpy", "jax"):
            eng = TopologyEngine(
                TopologyConfig(backend=backend, flush_threshold=10**9, num_landmarks=4)
            )
            feed_star(eng)
            eng.flush(now=1000.0)
            engines[backend] = eng
        assert isinstance(engines["numpy"].kernels, NumpyKernels)
        assert isinstance(engines["jax"].kernels, JaxKernels)
        np.testing.assert_allclose(
            np.asarray(engines["numpy"]._D),
            np.asarray(engines["jax"]._D),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(engines["numpy"]._khop_rtt),
            np.asarray(engines["jax"]._khop_rtt),
            rtol=1e-5,
        )
        for a, b in (("h0", "h1"), ("h1", "h2"), ("h2", "h5")):
            assert engines["numpy"].est_rtt_ns(a, b) == pytest.approx(
                engines["jax"].est_rtt_ns(a, b), rel=1e-5
            )


class TestStalenessDecay:
    def test_quiet_edges_lose_aggregation_weight(self):
        eng = make_engine(half_life_s=60.0)
        eng.enqueue("a", "b", rtt_ns=10 * MS, created_at=1000.0)
        eng.flush(now=1000.0)
        fresh = np.asarray(eng._weights).max()
        eng.flush(now=1000.0 + 120.0)  # two half-lives later
        stale = np.asarray(eng._weights).max()
        assert fresh == pytest.approx(1.0, abs=1e-5)
        assert stale == pytest.approx(0.25, rel=1e-3)

    def test_ancient_edges_purged(self):
        eng = make_engine(max_age_s=3600.0)
        eng.enqueue("a", "b", rtt_ns=10 * MS, created_at=4000.0)
        eng.enqueue("a", "c", rtt_ns=10 * MS, created_at=5000.0)
        eng.flush(now=5000.0)
        assert eng.stats()["edges"] == 2
        eng.flush(now=4000.0 + 3601.0)  # a→b past max age, a→c still inside
        assert eng.stats()["edges"] == 1
        assert eng.est_rtt_ns("a", "b") is None


class TestDeleteHostParity:
    def test_engine_purge_matches_kv_purge(self):
        hm = HostManager()
        for i in range(4):
            hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip="10.0.0.1", port=1))
        kv = KVStore()
        eng = make_engine()
        nt = NetworkTopology(kv, hm, None, engine=eng)
        for s in range(4):
            for d in range(4):
                if s != d:
                    nt.enqueue_probe(f"h{s}", Probe(f"h{d}", rtt_ns=5 * MS))
        eng.flush()
        assert eng.stats()["edges"] == 12

        nt.delete_host("h1")
        # KV side gone
        assert not nt.has_edge("h0", "h1") and not nt.has_edge("h1", "h2")
        # engine side gone too — including pending deltas and inferences
        assert eng.est_rtt_ns("h0", "h1") is None
        assert all(
            "h1" not in (eng.store.ids[s], eng.store.ids[d])
            for s, d in eng.store.edges
        )
        # both views export the same remaining edge set
        kv_edges = {
            tuple(k.split(":")[1:]) for k in kv.scan_iter("networktopology:*:*")
        }
        eng_edges = {
            (eng.store.ids[s], eng.store.ids[d]) for s, d in eng.store.edges
        }
        assert kv_edges == eng_edges

    def test_pending_deltas_do_not_resurrect_deleted_host(self):
        eng = make_engine()
        eng.enqueue("a", "b", rtt_ns=5 * MS)
        eng.enqueue("b", "c", rtt_ns=5 * MS)
        eng.delete_host("b")  # before any flush
        eng.flush()
        assert all(
            "b" not in (eng.store.ids[s], eng.store.ids[d])
            for s, d in eng.store.edges
        )


class TestExportAndSnapshot:
    def _nt(self, n=6, with_engine=True):
        hm = HostManager()
        for i in range(n):
            hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip=f"10.0.0.{i}", port=1))
        eng = make_engine() if with_engine else None
        return NetworkTopology(KVStore(), hm, None, engine=eng), hm

    def test_engine_export_feeds_gnn_without_kv_walk(self):
        nt, hm = self._nt()
        for s in range(6):
            for d in range(6):
                if s != d:
                    nt.enqueue_probe(f"h{s}", Probe(f"h{d}", rtt_ns=(5 + s + d) * MS))
        nt.kv.flushall()  # prove the export never touches KV
        recs = nt.export_records()
        assert len(recs) == 6
        from dragonfly2_tpu.schema.columnar import records_to_columns
        from dragonfly2_tpu.schema.features import build_probe_graph

        g = build_probe_graph(records_to_columns(recs), max_degree=4)
        assert g.num_nodes == 6
        assert len(g.edge_src) > 0

    def test_export_prefers_freshest_edges_engine_path(self):
        import time

        nt, hm = self._nt(n=6)
        base = time.time()  # export flushes with the real clock; stale-purge must not fire
        for d in range(1, 6):  # h0 → h1..h5, h5 updated last
            nt.enqueue_probe(
                "h0", Probe(f"h{d}", rtt_ns=5 * MS, created_at=base + d)
            )
        recs = nt.export_records(dest_limit=2)
        dest_ids = [dh.id for dh in recs[0].dest_hosts]
        assert dest_ids == ["h5", "h4"]  # most recently updated first

    def test_export_prefers_freshest_edges_kv_path(self):
        nt, hm = self._nt(n=6, with_engine=False)
        base = 1000.0
        for d in range(1, 6):
            nt.enqueue_probe(
                "h0", Probe(f"h{d}", rtt_ns=5 * MS, created_at=base + d)
            )
        recs = nt.export_records(dest_limit=2)
        dest_ids = [dh.id for dh in recs[0].dest_hosts]
        assert dest_ids == ["h5", "h4"]


class TestEvaluatorIntegration:
    def test_feature_dim_rejection_guards_schema_bump(self):
        from dragonfly2_tpu.scheduler.evaluator import MLEvaluator
        from dragonfly2_tpu.schema.features import MLP_FEATURE_DIM

        class Model:
            def __init__(self, dim):
                self.feature_dim = dim

            def predict(self, feats):
                return np.zeros(feats.shape[0], np.float32)

        ev = MLEvaluator()
        ev.set_model(Model(MLP_FEATURE_DIM - 1))  # pre-bump model
        assert ev._model is None  # refused loudly, not installed
        ev.set_model(Model(MLP_FEATURE_DIM))
        assert ev._model is not None

    def test_rtt_affinity_feature_position_and_value(self):
        from dragonfly2_tpu.scheduler import resource as res
        from dragonfly2_tpu.scheduler.evaluator import pair_features
        from dragonfly2_tpu.schema.features import MLP_FEATURE_NAMES

        t = res.Task("t")
        t.total_piece_count = 4
        child = res.Peer("c", t, res.Host(id="hc"))
        parent = res.Peer("p", t, res.Host(id="hp"))
        idx = MLP_FEATURE_NAMES.index("rtt_affinity")
        assert pair_features(parent, child, 4)[idx] == 0.0  # missing-value
        assert pair_features(parent, child, 4, rtt_affinity=0.3)[idx] == pytest.approx(
            0.3
        )


class TestEndToEnd:
    def test_probes_to_adjacency_to_ranking_shift(self):
        """The acceptance demo: probes enqueued through NetworkTopology
        appear in the device adjacency after a delta flush, an unprobed
        pair returns a finite landmark-inferred RTT, and MLEvaluator
        ranking measurably shifts when that RTT feature flips — on the
        numpy fallback path (this suite runs under JAX_PLATFORMS=cpu;
        conftest pins it)."""
        from dragonfly2_tpu.scheduler import resource as res
        from dragonfly2_tpu.scheduler.evaluator import MLEvaluator
        from dragonfly2_tpu.schema.features import MLP_FEATURE_DIM, MLP_FEATURE_NAMES

        hm = HostManager()
        for hid in ("child", "near", "far"):
            hm.store(Host(id=hid, hostname=hid, ip="10.0.0.1", port=1))
        eng = make_engine(flush_threshold=4)  # exercise auto-flush too
        nt = NetworkTopology(KVStore(), hm, None, engine=eng)

        # child↔near fast through the hub "child"; far is slow
        nt.enqueue_probe("child", Probe("near", rtt_ns=2 * MS))
        nt.enqueue_probe("near", Probe("child", rtt_ns=2 * MS))
        nt.enqueue_probe("child", Probe("far", rtt_ns=90 * MS))
        nt.enqueue_probe("far", Probe("child", rtt_ns=90 * MS))
        eng.flush()
        assert eng.stats()["edges"] == 4  # probes landed in the adjacency

        # unprobed pair (near, far): finite inferred estimate
        inferred = eng.est_rtt_ns("near", "far")
        assert inferred is not None and inferred == pytest.approx(92 * MS, rel=0.01)

        # a model that scores ONLY the rtt feature: predicted cost =
        # rtt_affinity, so topology is the only thing that can reorder
        rtt_idx = MLP_FEATURE_NAMES.index("rtt_affinity")

        class RttModel:
            feature_dim = MLP_FEATURE_DIM

            def predict(self, feats):
                return feats[:, rtt_idx]

        t = res.Task("t")
        t.total_piece_count = 4
        child = res.Peer("c", t, hm.load("child"))
        p_near = res.Peer("pn", t, hm.load("near"))
        p_far = res.Peer("pf", t, hm.load("far"))

        without = MLEvaluator(RttModel())  # no topology: feature is 0/0 → tie
        with_topo = MLEvaluator(RttModel(), topology=eng)
        ranked = with_topo.evaluate_parents([p_far, p_near], child, 4)
        assert [p.id for p in ranked] == ["pn", "pf"]  # near wins on RTT
        baseline = without.evaluate_parents([p_far, p_near], child, 4)
        assert [p.id for p in baseline] == ["pf", "pn"]  # tie → input order kept

        # flip the topology: far becomes the fast host
        nt.enqueue_probe("child", Probe("far", rtt_ns=1 * MS))
        nt.enqueue_probe("child", Probe("near", rtt_ns=95 * MS))
        eng.flush()
        reranked = with_topo.evaluate_parents([p_far, p_near], child, 4)
        assert [p.id for p in reranked] == ["pf", "pn"]  # ranking flipped

    def test_seed_placement_by_rtt_centrality(self):
        from dragonfly2_tpu.scheduler.seed_placement import recommend_seeds_by_rtt

        eng = make_engine()
        # h0 is the natural seed: fast from everyone; h5 slow
        for s in range(6):
            for d in range(6):
                if s != d:
                    rtt = 2 if d == 0 else (80 if d == 5 else 20)
                    eng.enqueue(f"h{s}", f"h{d}", rtt_ns=rtt * MS, created_at=1000.0)
        eng.flush(now=1000.0)
        ranking = recommend_seeds_by_rtt(eng, k=3)
        assert ranking[0]["host_id"] == "h0"
        assert all(r["host_id"] != "h5" for r in ranking)
        sub = recommend_seeds_by_rtt(eng, k=2, candidates=["h3", "h5"])
        assert [r["host_id"] for r in sub][0] == "h3"
        with pytest.raises(ValueError):
            recommend_seeds_by_rtt(eng, candidates=["unknown-host"])

    def test_topology_rpc_service(self):
        """EstRtt / Neighbors / Stats over the real gRPC glue."""
        import grpc

        from dragonfly2_tpu.rpc import glue
        from dragonfly2_tpu.rpc.glue import TOPOLOGY_SERVICE
        from dragonfly2_tpu.scheduler.topology_service import TopologyService
        from dragonfly2_tpu.rpc import gen  # noqa: F401
        import topology_pb2

        eng = make_engine()
        feed_star(eng)
        eng.flush(now=1000.0)
        server, port = glue.serve(
            {TOPOLOGY_SERVICE: TopologyService(eng)}, "127.0.0.1:0"
        )
        try:
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            client = glue.ServiceClient(channel, TOPOLOGY_SERVICE)
            direct = client.EstRtt(
                topology_pb2.EstRttRequest(src_host_id="h0", dest_host_id="h1")
            )
            assert direct.found and direct.source == "direct"
            assert direct.rtt_ns == 5 * MS
            inferred = client.EstRtt(
                topology_pb2.EstRttRequest(src_host_id="h1", dest_host_id="h2")
            )
            assert inferred.found and inferred.source == "inferred"
            missing = client.EstRtt(
                topology_pb2.EstRttRequest(src_host_id="h1", dest_host_id="nope")
            )
            assert not missing.found
            nbrs = client.Neighbors(
                topology_pb2.NeighborsRequest(host_id="h0", limit=3)
            )
            assert [n.host_id for n in nbrs.neighbors] == ["h1", "h2", "h3"]
            stats = client.Stats(topology_pb2.StatsRequest())
            assert stats.hosts == 6 and stats.edges == 10
            assert stats.backend == "numpy"
            channel.close()
        finally:
            server.stop(grace=0)

    def test_scheduler_server_wires_engine(self, tmp_path):
        """SchedulerServer builds the engine, mirrors SyncProbes into
        it, and serves the Topology RPC alongside the scheduling
        services."""
        import grpc

        from dragonfly2_tpu.rpc import glue
        from dragonfly2_tpu.rpc.glue import TOPOLOGY_SERVICE
        from dragonfly2_tpu.scheduler.server import (
            SchedulerServer,
            SchedulerServerConfig,
        )
        import topology_pb2

        srv = SchedulerServer(
            SchedulerServerConfig(
                data_dir=str(tmp_path), topology_backend="numpy"
            )
        )
        addr = srv.serve()
        try:
            assert srv.networktopology.engine is srv.topology_engine
            for hid in ("a", "b"):
                srv.resource.host_manager.store(
                    Host(id=hid, hostname=hid, ip="127.0.0.1", port=1)
                )
            srv.networktopology.enqueue_probe("a", Probe("b", rtt_ns=7 * MS))
            srv.topology_engine.flush()
            channel = grpc.insecure_channel(addr)
            client = glue.ServiceClient(channel, TOPOLOGY_SERVICE)
            resp = client.EstRtt(
                topology_pb2.EstRttRequest(src_host_id="a", dest_host_id="b")
            )
            assert resp.found and resp.rtt_ns == 7 * MS
            channel.close()
        finally:
            srv.stop()


class TestHydrationAndTrainJoin:
    def test_engine_adopts_peer_scheduler_edges_from_kv(self):
        """Multi-scheduler KV sharing: edges probed via a PEER scheduler
        (never through this process's enqueue_probe) must still appear
        in this scheduler's snapshot — hydration merges them from KV."""
        import time

        hm = HostManager()
        for i in range(4):
            hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip="10.0.0.1", port=1))
        kv = KVStore()  # the shared store
        peer_nt = NetworkTopology(kv, hm, None)  # peer scheduler: KV only
        local_nt = NetworkTopology(kv, hm, None, engine=make_engine())

        now = time.time()
        peer_nt.enqueue_probe("h2", Probe("h3", rtt_ns=9 * MS, created_at=now))
        local_nt.enqueue_probe("h0", Probe("h1", rtt_ns=4 * MS, created_at=now))

        recs = local_nt.export_records()  # hydrates, then engine-exports
        srcs = {r.host.id for r in recs}
        assert srcs == {"h0", "h2"}  # the peer's edge made it in
        assert local_nt.engine.est_rtt_ns("h2", "h3") == 9 * MS

    def test_adopt_never_clobbers_fresher_local_state(self):
        import time

        now = time.time()
        eng = make_engine()
        assert eng.adopt("a", "b", 10 * MS, updated_at=now - 10)
        assert not eng.adopt("a", "b", 99 * MS, updated_at=now - 20)  # older
        assert eng.adopt("a", "b", 20 * MS, updated_at=now)  # newer
        eng.flush()
        assert eng.est_rtt_ns("a", "b") == 20 * MS

    def test_block_encode_joins_live_rtt_into_training_data(self, tmp_path):
        """Train/serve agreement: with the engine's lookup installed on
        scheduler Storage, the binary train blocks carry live
        rtt_affinity values — not the constant 0.0 the model could
        never learn from."""
        import time

        from dragonfly2_tpu.schema import synth, wire
        from dragonfly2_tpu.schema.features import MLP_FEATURE_NAMES

        recs = synth.make_download_records(20, seed=0)
        child_ids = {r.host.id for r in recs}
        parent_ids = {p.host.id for r in recs for p in r.parents if p.host.id}
        eng = make_engine()
        now = time.time()
        for c in child_ids:
            for p in parent_ids:
                if c != p:
                    eng.enqueue(c, p, rtt_ns=12 * MS, created_at=now)
        eng.flush()

        blk = wire.encode_train_block(recs, rtt_lookup=eng.rtt_affinity_batch)
        path = tmp_path / "t.dfb"
        path.write_bytes(blk)
        feats = None
        for feats, _, _ in wire.stream_train_pairs(path, passes=1):
            pass
        idx = MLP_FEATURE_NAMES.index("rtt_affinity")
        col = feats[:, idx]
        assert (col > 0).any(), "live rtt must reach the training tensors"
        expect = float(np.log1p(12.0) / 10.0)
        assert np.allclose(col[col > 0], expect, rtol=1e-5)

        # without the lookup the column stays at the missing-value
        blk0 = wire.encode_train_block(recs)
        path.write_bytes(blk0)
        for feats0, _, _ in wire.stream_train_pairs(path, passes=1):
            pass
        assert (feats0[:, idx] == 0.0).all()

    def test_est_rtt_detail_provenance(self):
        eng = make_engine()
        feed_star(eng, spokes=2)
        eng.flush(now=1000.0)
        assert eng.est_rtt_detail("h0", "h0") == (0, "self")
        assert eng.est_rtt_detail("h0", "h1")[1] == "direct"
        assert eng.est_rtt_detail("h1", "h2")[1] == "inferred"
        assert eng.est_rtt_detail("h1", "ghost") == (None, "none")
        # cached answers keep their provenance
        assert eng.est_rtt_detail("h1", "h2")[1] == "inferred"


class TestKVBatching:
    def test_find_probed_hosts_uses_mget_when_available(self):
        class CountingKV(KVStore):
            def __init__(self):
                super().__init__()
                self.gets = 0
                self.mgets = 0

            def get(self, key):
                self.gets += 1
                return super().get(key)

            def mget(self, keys):
                self.mgets += 1
                return [super(CountingKV, self).get(k) for k in keys]

        hm = HostManager()
        for i in range(30):
            hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip="10.0.0.1", port=1))
        kv = CountingKV()
        nt = NetworkTopology(kv, hm, None)
        for _ in range(3):
            nt.enqueue_probe("h0", Probe("h1", rtt_ns=MS))
        kv.gets = kv.mgets = 0
        got = nt.find_probed_hosts("h0")
        assert len(got) == nt.probe_count
        assert kv.mgets == 1  # ONE batched read for all candidates
        assert kv.gets == 0
        assert "h1" not in [h.id for h in got]  # ordering still least-probed

    def test_remote_mget_over_kvserver(self):
        from dragonfly2_tpu.utils.kvserver import KVServer
        from dragonfly2_tpu.utils.kvstore import RemoteKVStore

        server = KVServer(host="127.0.0.1", port=0)
        port = server.serve()
        try:
            kv = RemoteKVStore(f"127.0.0.1:{port}")
            kv.set("k1", "10")
            kv.set("k3", "30")
            assert kv.mget(["k1", "missing", "k3"]) == ["10", None, "30"]
            assert kv.mget([]) == []
            kv.close()
        finally:
            server.stop()

    def test_remote_hget_batch_pipelined(self):
        """Pipelined HGET over the real RESP wire: results align with
        the key order, missing keys/fields are None."""
        from dragonfly2_tpu.utils.kvserver import KVServer
        from dragonfly2_tpu.utils.kvstore import RemoteKVStore

        server = KVServer(host="127.0.0.1", port=0)
        port = server.serve()
        try:
            kv = RemoteKVStore(f"127.0.0.1:{port}")
            kv.hset("e1", {"updatedAt": "100", "averageRTT": "5"})
            kv.hset("e2", {"updatedAt": "200"})
            got = kv.hget_batch(["e1", "nope", "e2"], "updatedAt")
            assert got == ["100", None, "200"]
            assert kv.hget_batch([], "updatedAt") == []
            kv.close()
        finally:
            server.stop()


def test_concurrent_flush_and_export_do_not_deadlock():
    """Lock-order regression: the 30s GC flush (flush: _flush_lock →
    _lock) runs concurrently with the snapshot export (which must call
    flush BEFORE taking _lock — the old under-lock call ABBA-deadlocked
    in seconds)."""
    import threading
    import time

    from dragonfly2_tpu.scheduler.resource import HostManager

    hm = HostManager()
    for i in range(8):
        hm.store(Host(id=f"h{i}", hostname=f"n{i}", ip="10.0.0.1", port=1))
    eng = make_engine()
    now = time.time()
    for s in range(8):
        for d in range(8):
            if s != d:
                eng.enqueue(f"h{s}", f"h{d}", rtt_ns=5 * MS, created_at=now)
    stop = time.time() + 2.0
    errors: list = []

    def worker(fn):
        try:
            while time.time() < stop:
                fn()
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(eng.flush,)),
        threading.Thread(target=worker, args=(lambda: eng.export_records(hm, 5),)),
        threading.Thread(target=worker, args=(lambda: eng.centrality(),)),
        threading.Thread(target=worker, args=(lambda: eng.est_rtt_ns("h1", "h2"),)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    assert not errors
    assert not any(t.is_alive() for t in threads), "engine deadlocked"


@pytest.mark.slow
def test_topology_soak_large_graph():
    """Soak: a few thousand hosts through repeated delta flushes keeps
    queries finite and the flush latency bounded (marked slow: >5s)."""
    rng = np.random.default_rng(0)
    eng = make_engine(num_landmarks=16)
    n = 2000
    for i in range(40_000):
        s, d = rng.integers(0, n, size=2)
        if s == d:
            continue
        eng.enqueue(f"h{s}", f"h{d}", int(rng.integers(1, 80)) * MS, 1000.0 + i * 0.01)
        if i % 4096 == 0:
            eng.flush(now=1000.0 + i * 0.01)
    eng.flush(now=1000.0 + 40_000 * 0.01)
    stats = eng.stats()
    assert stats["hosts"] == n
    hits = 0
    for _ in range(500):
        a, b = rng.integers(0, n, size=2)
        if eng.est_rtt_ns(f"h{a}", f"h{b}") is not None:
            hits += 1
    assert hits > 400  # the landmark scheme covers most unprobed pairs
