"""Graph-parallel GraphSAGE vs the single-device oracle.

The sharded forward (node tables rotated around the ICI ring) must match
models.gnn.forward_edge_rtt elementwise in float32 — same masked-mean
aggregation, same head — and the sharded fit must actually learn.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dragonfly2_tpu.models import gnn as gnn_mod
from dragonfly2_tpu.models import gnn_sharded as gs
from dragonfly2_tpu.parallel.mesh import make_mesh
from dragonfly2_tpu.schema.columnar import records_to_columns
from dragonfly2_tpu.schema.features import build_probe_graph
from dragonfly2_tpu.schema.synth import make_topology_records


@pytest.fixture(scope="module")
def graph():
    recs = make_topology_records(150, num_hosts=30, seed=5)
    return build_probe_graph(records_to_columns(recs), max_degree=8, seed=0)


@pytest.fixture(scope="module")
def gp_mesh():
    return make_mesh(gp=8)


def test_sharded_forward_matches_oracle(graph, gp_mesh):
    key = jax.random.PRNGKey(0)
    params = gnn_mod.init_graphsage(
        key, graph.node_features.shape[1], [32, 32], num_nodes=graph.num_nodes
    )
    shards = 8
    nf, nbrs, mask, src, dst, y, w = gs.pad_graph(graph, shards)
    embed = gs.pad_rows(np.asarray(params["node_embed"]), shards)
    dense = {k: v for k, v in params.items() if k != "node_embed"}
    arrs = gs.shard_graph_arrays(gp_mesh, "gp", nf, nbrs, mask, src, dst)
    embed_d = gs.shard_graph_arrays(gp_mesh, "gp", embed)[0]

    fwd = gs.make_sharded_forward(gp_mesh, "gp", compute_dtype=jnp.float32)
    got = np.asarray(jax.jit(fwd)(dense, embed_d, *arrs))[: len(graph.edge_src)]

    # compare against a float32 oracle (the default oracle runs bf16
    # matmuls; float32 on both sides makes the comparison tight)
    def oracle_f32(params, feats, nbrs, mask, src, dst):
        emb = gnn_mod.apply_graphsage(params, feats, nbrs, mask, compute_dtype=jnp.float32)
        return gnn_mod.predict_edge(params, emb, src, dst)

    want = np.asarray(
        oracle_f32(
            params,
            jnp.asarray(graph.node_features),
            jnp.asarray(graph.neighbors),
            jnp.asarray(graph.neighbor_mask),
            jnp.asarray(graph.edge_src),
            jnp.asarray(graph.edge_dst),
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_training_learns(graph, gp_mesh):
    from dragonfly2_tpu.trainer.train import GNNFitConfig, train_gnn_sharded

    result = train_gnn_sharded(
        graph,
        gp_mesh,
        config=GNNFitConfig(hidden_dims=(32, 32), epochs=30, learning_rate=2e-2),
    )
    assert result.history[-1] < result.history[0], "loss should decrease"
    assert {"mse", "mae", "precision", "recall", "f1"} <= set(result.metrics)
    assert np.isfinite(result.metrics["mse"])


def test_pad_graph_even_shards(graph):
    nf, nbrs, mask, src, dst, y, w = gs.pad_graph(graph, 8)
    assert nf.shape[0] % 8 == 0
    assert src.shape[0] % 8 == 0
    # padded nodes self-neighbor, padded edges weight 0
    assert (nbrs[graph.num_nodes :] >= graph.num_nodes).all()
    assert w[len(graph.edge_src) :].sum() == 0
    assert (mask[graph.num_nodes :] == 0).all()


def test_distributed_init_noop_without_coordinator(monkeypatch):
    """Single-host boxes and CI: ensure_initialized is a clean no-op
    (the multi-host path needs a coordinator only a launcher provides)."""
    import dragonfly2_tpu.parallel.distributed as D

    monkeypatch.delenv("DF_JAX_COORDINATOR", raising=False)
    assert D.ensure_initialized() is False

    monkeypatch.setenv("DF_JAX_COORDINATOR", "10.0.0.1:1234")
    monkeypatch.delenv("DF_JAX_NUM_PROCESSES", raising=False)
    import pytest

    with pytest.raises(ValueError, match="DF_JAX_NUM_PROCESSES"):
        D.ensure_initialized()
