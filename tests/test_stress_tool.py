"""Stress load generator (reference test/tools/stress) against an
in-process cluster."""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
from dragonfly2_tpu.rpc.glue import serve
from dragonfly2_tpu.scheduler import resource as res
from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
from dragonfly2_tpu.scheduler.service import SERVICE_NAME as SCHED_SERVICE
from dragonfly2_tpu.scheduler.service import SchedulerService
from dragonfly2_tpu.tools import stress


@pytest.fixture
def cluster(tmp_path):
    payload = os.urandom(64 * 1024)

    class Origin(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_HEAD(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()

    origin = ThreadingHTTPServer(("127.0.0.1", 0), Origin)
    threading.Thread(target=origin.serve_forever, daemon=True).start()

    service = SchedulerService(
        res.Resource(),
        Scheduling(BaseEvaluator(), SchedulingConfig(retry_interval=0.05)),
    )
    server, port = serve({SCHED_SERVICE: service})
    d = Daemon(
        DaemonConfig(
            data_dir=str(tmp_path / "d"),
            scheduler_address=f"127.0.0.1:{port}",
            hostname="stress-host",
            ip="127.0.0.1",
            announce_interval=60.0,
        )
    )
    d.start()
    yield {
        "daemon": f"127.0.0.1:{d.port}",
        "origin": f"http://127.0.0.1:{origin.server_port}",
        "payload": payload,
    }
    d.stop()
    server.stop(0)
    origin.shutdown()
    origin.server_close()


def test_stress_daemon_mode_counts_and_percentiles(cluster):
    stats = stress.run(
        cluster["origin"] + "/obj-{i}.bin",
        daemon=cluster["daemon"],
        connections=3,
        requests=12,
    )
    assert stats["requests"] >= 12 and stats["failures"] == 0
    assert stats["bytes"] >= 12 * 64 * 1024
    lat = stats["latency_s"]
    assert 0 < lat["min"] <= lat["p50"] <= lat["p90"] <= lat["p99"] <= lat["max"]
    assert stats["rps"] > 0 and stats["throughput_mb_s"] > 0


def test_stress_duration_stop_and_csv(cluster, tmp_path):
    out = tmp_path / "samples.csv"
    stats = stress.run(
        cluster["origin"] + "/one.bin",  # single task: dedup/reuse path
        daemon=cluster["daemon"],
        connections=2,
        duration=2.0,
        output=str(out),
    )
    assert stats["requests"] > 0
    lines = out.read_text().strip().splitlines()
    assert lines[0] == "ok,seconds,bytes,error"
    assert len(lines) == stats["requests"] + 1


def test_stress_cli_json_line(cluster, capsys):
    rc = stress.main(
        [
            "--url", cluster["origin"] + "/cli-{i}.bin",
            "--daemon", cluster["daemon"],
            "-c", "2", "-n", "4",
        ]
    )
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["failures"] == 0 and parsed["requests"] >= 4


def test_stress_requires_exactly_one_target():
    with pytest.raises(ValueError):
        stress.run("http://x", daemon="a", proxy="b", requests=1)
    with pytest.raises(ValueError):
        stress.run("http://x", requests=1)


def test_shard_kill_soak_success_and_bounded_blackout():
    """Acceptance (ISSUE 8): 3 real scheduler shards under KV leases,
    simulated-peer announce load, one shard SIGKILL'd mid-load —
    success rate must be 1.0 with zero hangs, and the measured
    ``fleet_blackout_ms`` bounded by one lease TTL + one membership
    poll + announce/backoff slack. Deterministic: the blackout ends
    when the dead lease expires, not on a race.

    With the telemetry plane riding along (ISSUE 9), the manager's
    view of the kill must MATCH the daemon-measured one: the victim's
    shard flips stale within the staleness envelope of the same
    SIGKILL, and the manager aggregates live schedule ops across the
    surviving shards."""
    lease_ttl, poll = 1.5, 0.3
    stats = stress.shard_kill_soak(
        peers=60,
        shards=3,
        workers=8,
        lease_ttl=lease_ttl,
        renew_interval=0.4,
        poll_interval=poll,
    )
    assert stats["fleet_success_rate"] == 1.0, stats
    assert stats["fleet_hangs"] == 0
    assert stats["fleet_shards"] == 3
    # blackout: bounded below by ~nothing, above by TTL + poll + slack
    assert 0 <= stats["fleet_blackout_ms"] <= (lease_ttl + poll + 3.0) * 1e3, stats
    assert stats["schedule_ops_per_s"] > 0
    assert stats["fleet_wrong_shard_retries"] > 0  # the window was real
    # the manager's view of the member kill (telemetry plane): all 3
    # shards reported in, and the victim flipped stale within the
    # staleness envelope of the SAME SIGKILL the announce plane measured:
    # last push ≤0.5s before the kill + staleness floor 5s + soak poll
    # 0.25s + scheduling slack — i.e. the manager detects the kill at
    # its own (coarser) granularity, never misses it, never pre-dates it
    assert "fleet_telemetry_error" not in stats, stats
    assert stats["fleet_manager_shards"] == 3
    # staleness floor is 5s (max(3×0.5s push interval, 5.0)): detection
    # can't physically land before ~4.5s (last push up to 0.5s pre-kill)
    # and must land within floor + push/poll/scheduling slack
    assert 3_000 <= stats["fleet_manager_blackout_ms"] <= 9_000, stats
    assert stats["fleet_manager_schedule_ops_per_s"] > 0
    json.dumps(stats)  # one JSON-serializable line


def test_shard_kill_cli_gates_on_success(capsys):
    rc = stress.main(["--chaos", "--shard-kill", "--shard-peers", "30"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert rc == 0, parsed
    assert parsed["fleet_success_rate"] == 1.0


def test_serving_soak_batched_beats_per_call():
    """Acceptance (ISSUE 13): at ≥32 concurrent simulated peers the
    batched scoring service's aggregate ``schedule_ops_per_s`` is
    strictly above the per-call baseline (same model, same candidate
    sets), zero submissions are lost, and the p99 decision latency
    stays inside the batching window + single-batch service time
    (the tool's measured ``serving_p99_bound_us``)."""
    stats = stress.serving_soak(peers=32, decisions_per_peer=15)
    assert stats["serving_lost"] == 0, stats
    assert (
        stats["schedule_ops_per_s"] > stats["schedule_ops_per_s_per_call"]
    ), stats
    # co-batching really happened: more than one request per batch
    assert stats["evaluator_batch_occupancy"] > stats["serving_candidates"], stats
    assert (
        0 < stats["schedule_decision_p99_us"] <= stats["serving_p99_bound_us"]
    ), stats
    json.dumps(stats)  # one JSON-serializable line


def test_serving_soak_cli_gates(capsys):
    rc = stress.main(["--serving", "--serving-peers", "16",
                      "--serving-decisions", "10"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert rc == 0, parsed
    assert parsed["serving_lost"] == 0


def test_wave_soak_packed_beats_per_op():
    """Acceptance (ISSUE 16): wave-packed scheduling's aggregate
    ``wave_decisions_per_s`` is strictly above the per-op-batched
    baseline (same model, same candidate sets), zero submissions are
    lost, rankings stay bit-identical to a serving-free per-peer
    evaluator, and the reported occupancy shows whole waves packing
    (rows per wave > candidates per decision)."""
    stats = stress.wave_soak(peers=24, decisions_per_peer=12)
    assert stats["wave_lost"] == 0, stats
    assert stats["wave_rankings_match"] == 1, stats
    assert (
        stats["wave_decisions_per_s"] > stats["wave_decisions_per_s_per_op"]
    ), stats
    assert stats["wave_occupancy_rows"] > stats["wave_candidates"], stats
    assert stats["wave_unpack_p99_us"] > 0, stats
    json.dumps(stats)  # one JSON-serializable line


def test_wave_soak_cli_gates(capsys):
    rc = stress.main(["--serving", "--wave", "--serving-peers", "16",
                      "--serving-decisions", "10"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert rc == 0, parsed
    assert parsed["wave_lost"] == 0
    assert parsed["wave_rankings_match"] == 1


def test_soak_ingest_tool_reports_bounded_memory():
    """The soak tool streams a multi-shard dataset and reports flat RSS
    (working set independent of decoded bytes — the 1B-record property).
    Decode volume is verified by MEASUREMENT: two passes must count
    exactly twice one pass's records, untruncated."""
    import json as _json

    from dragonfly2_tpu.tools import soak_ingest

    one = soak_ingest.run(mb=48, passes=1, batch_size=8192, steps_per_call=2, workers=1)
    two = soak_ingest.run(mb=48, passes=2, batch_size=8192, steps_per_call=2, workers=1)
    assert not one["truncated"] and not two["truncated"]
    assert one["records"] > 0
    assert two["records"] == 2 * one["records"]
    # growth must be a small fraction of what flowed through (generous
    # bound: jit arenas and allocator slack are real, hoarding is not)
    assert two["rss_growth_mb"] < two["decoded_mb"]
    _json.dumps(two)  # one JSON-serializable line
