"""W3C trace-context propagation: traceparent encode/parse, contextvar
parenting, sampling, client-side RPC instrumentation, and the
end-to-end single-trace guarantee — one trace_id from the dfget client
call through the daemon conductor, the scheduler's rpc/scheduling
spans, and the trainer's fit."""

import json
import threading

import pytest

from dragonfly2_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _full_sampling():
    """Tests assert recorded spans; pin the ratio in case another test
    (or env) lowered it."""
    prev = tracing._sample_ratio
    tracing._sample_ratio = 1.0
    yield
    tracing._sample_ratio = prev


# ---------------------------------------------------------------------------
# traceparent encode/parse
# ---------------------------------------------------------------------------


def test_traceparent_round_trip():
    tr = tracing.Tracer("svc")
    span = tr.start_span("x")
    header = tracing.format_traceparent(span)
    assert header == f"00-{span.trace_id}-{span.span_id}-01"
    ctx = tracing.parse_traceparent(header)
    assert ctx is not None
    assert ctx.trace_id == span.trace_id
    assert ctx.span_id == span.span_id
    assert ctx.sampled is True

    # unsampled flags round-trip too
    tracing._sample_ratio = 0.0
    unsampled = tr.start_span("y")
    header = tracing.format_traceparent(unsampled)
    assert header.endswith("-00")
    ctx = tracing.parse_traceparent(header)
    assert ctx is not None and ctx.sampled is False


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        "00-abc-def-01",  # ids too short
        "zz-" + "a" * 32 + "-" + "b" * 16 + "-01",  # bad version chars
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # forbidden version
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # all-zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
        "00-" + "A" * 32 + "-" + "b" * 16,  # missing flags
    ],
)
def test_malformed_traceparent_falls_back_to_new_root(header):
    assert tracing.parse_traceparent(header) is None
    # and a span started with that parse result is a fresh root — no crash
    span = tracing.Tracer("svc").start_span("x", parent=tracing.parse_traceparent(header))
    assert span.parent_id == ""
    assert len(span.trace_id) == 32


def test_parse_accepts_uppercase_and_whitespace():
    ctx = tracing.parse_traceparent("  00-" + "A" * 32 + "-" + "B" * 16 + "-01\n")
    assert ctx is not None and ctx.trace_id == "a" * 32


# ---------------------------------------------------------------------------
# contextvar parenting + sampling
# ---------------------------------------------------------------------------


def test_contextvar_auto_parenting():
    tr = tracing.Tracer("svc")
    with tr.span("root") as root:
        auto = tr.start_span("auto")
        assert auto.trace_id == root.trace_id
        assert auto.parent_id == root.span_id
    # block exited: no current span, a fresh start is a root again
    fresh = tr.start_span("fresh")
    assert fresh.parent_id == "" and fresh.trace_id != root.trace_id


def test_use_span_hands_context_across_threads():
    tr = tracing.Tracer("svc")
    root = tr.start_span("root")
    seen = {}

    def worker():
        with tracing.use_span(root):
            seen["span"] = tr.start_span("in-thread")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["span"].trace_id == root.trace_id
    assert seen["span"].parent_id == root.span_id


def test_unsampled_spans_skip_all_sinks(tmp_path):
    tr = tracing.Tracer("unsampled-svc", export_path=str(tmp_path / "s.jsonl"))
    tracing._sample_ratio = 0.0
    with tr.span("root") as root:
        assert root.sampled is False
        child = tr.start_span("child")
        assert child.sampled is False  # inherits the root's decision
        child.end()
    assert len(tr.finished) == 0  # ring skipped
    assert (tmp_path / "s.jsonl").read_text() == ""  # file skipped
    # sampled spans still record
    tracing._sample_ratio = 1.0
    tr.start_span("real").end()
    assert len(tr.finished) == 1
    tr.close()


def test_remote_unsampled_parent_suppresses_subtree():
    tr = tracing.Tracer("svc")
    ctx = tracing.parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-00")
    span = tr.start_span("rpc.X", parent=ctx)
    assert span.sampled is False
    span.end()
    assert all(s.name != "rpc.X" for s in tr.finished)


def test_is_sampling_and_maybe_span():
    tracing._sample_ratio = 1.0
    assert tracing.is_sampling() is True
    tr = tracing.get("maybe-test")
    with tracing.maybe_span("maybe-test", "visible") as sp:
        assert sp.sampled
    assert tr.finished[-1].name == "visible"
    n = len(tr.finished)
    tracing._sample_ratio = 0.0
    assert tracing.is_sampling() is False
    with tracing.maybe_span("maybe-test", "invisible") as sp:
        assert not sp.sampled
    assert len(tr.finished) == n  # nothing recorded
    # under an unsampled current span, is_sampling follows the span
    tracing._sample_ratio = 1.0
    with tracing.use_span(tracing.NOOP_SPAN):
        assert tracing.is_sampling() is False


# ---------------------------------------------------------------------------
# configure() staleness (satellite): cached tracers must rebind
# ---------------------------------------------------------------------------


def test_configure_rebinds_cached_tracers(tmp_path):
    service = "rebind-test"
    try:
        tracing.configure(str(tmp_path / "dir1"))
        tr = tracing.get(service)
        tr.start_span("first").end()
        # a LATER configure must take effect on the already-cached tracer
        tracing.configure(str(tmp_path / "dir2"))
        assert tracing.get(service) is tr  # same instance, rebound
        tr.start_span("second").end()
        lines1 = (tmp_path / "dir1" / f"{service}.spans.jsonl").read_text().splitlines()
        lines2 = (tmp_path / "dir2" / f"{service}.spans.jsonl").read_text().splitlines()
        assert [json.loads(l)["name"] for l in lines1] == ["first"]
        assert [json.loads(l)["name"] for l in lines2] == ["second"]
        # clearing the dir drops file export without killing the tracer
        tracing.configure(None)
        tr.start_span("third").end()
        assert tr.export_path is None
        assert len((tmp_path / "dir2" / f"{service}.spans.jsonl").read_text().splitlines()) == 1
    finally:
        tracing.configure(None)


# ---------------------------------------------------------------------------
# real-gRPC propagation
# ---------------------------------------------------------------------------


def _scheduler_stack(tmp_path=None, storage=None):
    from dragonfly2_tpu.rpc.glue import serve
    from dragonfly2_tpu.scheduler import resource as res
    from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
    from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
    from dragonfly2_tpu.scheduler.service import SERVICE_NAME, SchedulerService

    service = SchedulerService(
        res.Resource(),
        Scheduling(BaseEvaluator(), SchedulingConfig(retry_interval=0.0)),
        storage=storage,
    )
    server, port = serve({SERVICE_NAME: service})
    return server, port, SERVICE_NAME


def test_client_wrapper_injects_and_server_parents(tmp_path):
    """Unary RPC: the client span joins the caller's trace, the server
    span parents under the CLIENT span via the traceparent header, and
    the rpc_client_* series tick."""
    from dragonfly2_tpu.rpc import gen  # noqa: F401 — flat pb2 imports
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc import glue

    server, port, svc_name = _scheduler_stack()
    chan = glue.dial(f"127.0.0.1:{port}")
    try:
        client = glue.ServiceClient(chan, svc_name)
        handled, latency = glue._rpc_client_metrics()
        before = handled.labels(svc_name, "AnnounceHost", "OK")._value
        with tracing.get("testsvc").span("caller") as caller:
            client.AnnounceHost(
                scheduler_pb2.AnnounceHostRequest(
                    host=common_pb2.HostInfo(id="h-trace", ip="127.0.0.1", hostname="x")
                )
            )
        assert handled.labels(svc_name, "AnnounceHost", "OK")._value == before + 1
        assert latency.labels(svc_name, "AnnounceHost").count >= 1
        # client span recorded in the caller's tracer, in the caller's trace
        client_spans = [
            s
            for s in tracing.get("testsvc").finished
            if s.name == "rpc.AnnounceHost" and s.trace_id == caller.trace_id
        ]
        assert client_spans and client_spans[-1].parent_id == caller.span_id
        # server span parented under the CLIENT span — one continuous trace
        server_spans = [
            s
            for s in tracing.get("Scheduler").finished
            if s.name == "rpc.AnnounceHost" and s.trace_id == caller.trace_id
        ]
        assert server_spans
        assert server_spans[-1].parent_id == client_spans[-1].span_id
    finally:
        chan.close()
        server.stop(0)


def test_malformed_header_on_the_wire_starts_new_root():
    """A garbage traceparent in invocation metadata must not crash the
    handler — the server span becomes a fresh root."""
    from dragonfly2_tpu.rpc import gen  # noqa: F401 — flat pb2 imports
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc import glue

    server, port, svc_name = _scheduler_stack()
    chan = glue.dial(f"127.0.0.1:{port}")
    try:
        # a raw callable, bypassing the instrumented client wrapper, so
        # the malformed header is what actually rides the wire
        raw = chan.unary_unary(
            f"/{svc_name}/AnnounceHost",
            request_serializer=scheduler_pb2.AnnounceHostRequest.SerializeToString,
            response_deserializer=scheduler_pb2.Empty.FromString,
        )
        raw(
            scheduler_pb2.AnnounceHostRequest(
                host=common_pb2.HostInfo(id="h-mal", ip="127.0.0.1", hostname="m")
            ),
            metadata=(("traceparent", "00-not-a-trace-01"),),
        )
        spans = [s for s in tracing.get("Scheduler").finished if s.name == "rpc.AnnounceHost"]
        assert spans and spans[-1].parent_id == ""  # fresh root, handled OK
    finally:
        chan.close()
        server.stop(0)


def test_explicit_caller_traceparent_wins():
    """A caller that already set a traceparent header keeps it — the
    wrapper must not stack a second one."""
    from dragonfly2_tpu.rpc import gen  # noqa: F401 — flat pb2 imports
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc import glue

    server, port, svc_name = _scheduler_stack()
    chan = glue.dial(f"127.0.0.1:{port}")
    try:
        client = glue.ServiceClient(chan, svc_name)
        explicit = "00-" + "c" * 32 + "-" + "d" * 16 + "-01"
        client.AnnounceHost(
            scheduler_pb2.AnnounceHostRequest(
                host=common_pb2.HostInfo(id="h-exp", ip="127.0.0.1", hostname="e")
            ),
            metadata=(("traceparent", explicit),),
        )
        spans = [s for s in tracing.get("Scheduler").finished if s.name == "rpc.AnnounceHost"]
        assert spans and spans[-1].trace_id == "c" * 32
        assert spans[-1].parent_id == "d" * 16
    finally:
        chan.close()
        server.stop(0)


def test_abandoned_response_stream_finalizes_span_and_series(tmp_path):
    """A caller that stops iterating a response stream early (dfget
    returns on the first done=True) must still complete the client span
    and the rpc_client series — finalized at GC with code ABANDONED."""
    import gc

    from dragonfly2_tpu.client import dfget
    from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
    from dragonfly2_tpu.rpc import glue

    server, port, _ = _scheduler_stack()
    d = Daemon(
        DaemonConfig(
            data_dir=str(tmp_path / "daemon"),
            scheduler_address=f"127.0.0.1:{port}",
            hostname="host-abandon",
            piece_length=32 * 1024,
            announce_interval=60.0,
        )
    )
    d.start()
    try:
        import os

        origin = tmp_path / "o.bin"
        origin.write_bytes(os.urandom(8 * 1024))
        handled, _ = glue._rpc_client_metrics()
        child = handled.labels(glue.DFDAEMON_SERVICE, "Download", "ABANDONED")
        before = child.value
        with tracing.get("abandontest").span("dl") as root:
            dfget.download(
                f"127.0.0.1:{d.port}", f"file://{origin}", str(tmp_path / "out.bin")
            )
        gc.collect()
        assert child.value == before + 1
        spans = [
            s
            for s in tracing.get("abandontest").finished
            if s.name == "rpc.Download" and s.trace_id == root.trace_id
        ]
        assert spans and spans[-1].status == "abandoned"
    finally:
        d.stop()
        server.stop(0)


def test_single_trace_across_download_schedule_and_fit(tmp_path):
    """The acceptance chain: ONE trace_id spans the dfget client call,
    the daemon's conductor span, the scheduler's rpc.AnnouncePeer +
    schedule spans, and — through the announcer's upload — the
    trainer's rpc.Train + fit spans."""
    import os

    from dragonfly2_tpu.client import dfget
    from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
    from dragonfly2_tpu.rpc.glue import TRAINER_SERVICE, dial, serve
    from dragonfly2_tpu.scheduler.announcer import Announcer
    from dragonfly2_tpu.scheduler.storage import Storage
    from dragonfly2_tpu.schema import synth
    from dragonfly2_tpu.trainer.service import TrainerService
    from dragonfly2_tpu.trainer.storage import TrainerStorage
    from dragonfly2_tpu.trainer.train import FitConfig, GNNFitConfig
    from dragonfly2_tpu.trainer.training import Training, TrainingConfig

    # scheduler with a record sink, pre-seeded so the fit has data
    storage = Storage(tmp_path / "rec", buffer_size=1)
    for r in synth.make_download_records(60, seed=5):
        storage.create_download(r)
    storage.flush()
    server, port, _ = _scheduler_stack(storage=storage)

    # trainer with synchronous fits (the fit runs inside the Train RPC)
    t_storage = TrainerStorage(tmp_path / "trainer")
    training = Training(
        t_storage,
        manager_client=None,
        config=TrainingConfig(
            mlp=FitConfig(hidden_dims=(16,), batch_size=64, epochs=2, seed=0),
            gnn=GNNFitConfig(hidden_dims=(8,), batch_size=64, epochs=5, seed=0),
            gru=False,
        ),
    )
    t_server, t_port = serve(
        {TRAINER_SERVICE: TrainerService(t_storage, training, synchronous=True)}
    )
    t_channel = dial(f"127.0.0.1:{t_port}")

    d = Daemon(
        DaemonConfig(
            data_dir=str(tmp_path / "daemon"),
            scheduler_address=f"127.0.0.1:{port}",
            hostname="host-onetrace",
            piece_length=32 * 1024,
            announce_interval=60.0,
        )
    )
    d.start()
    try:
        payload = os.urandom(64 * 1024)
        origin = tmp_path / "o.bin"
        origin.write_bytes(payload)
        out = tmp_path / "out.bin"
        with tracing.get("e2e-test").span("one-trace") as root:
            dfget.download(f"127.0.0.1:{d.port}", f"file://{origin}", str(out))
            storage.flush()
            ann = Announcer(
                storage,
                ip="10.1.1.1",
                hostname="sched-trace",
                trainer_channel=t_channel,
            )
            assert ann.train_once()
        assert out.read_bytes() == payload
    finally:
        d.stop()
        t_channel.close()
        t_server.stop(0)
        server.stop(0)

    t = root.trace_id

    def in_trace(service, name):
        return [
            s
            for s in tracing.get(service).finished
            if s.name == name and s.trace_id == t
        ]

    # dfdaemon: the conductor's peer_task span, parented under the
    # daemon's rpc.Download server span
    peer_tasks = in_trace("dfdaemon", "peer_task")
    assert peer_tasks, "conductor span missing from the trace"
    downloads = in_trace("Dfdaemon", "rpc.Download")
    assert downloads
    assert peer_tasks[-1].parent_id in {s.span_id for s in downloads}

    # scheduler: rpc.AnnouncePeer (parent: the conductor's client call)
    # and the scheduling decision under it
    announces = in_trace("Scheduler", "rpc.AnnouncePeer")
    assert announces, "scheduler rpc span missing from the trace"
    schedules = in_trace("scheduler", "schedule")
    assert schedules, "scheduling span missing from the trace"
    assert schedules[-1].parent_id in {s.span_id for s in announces}

    # trainer: rpc.Train under the announcer's upload span, fit under it
    trains = in_trace("Trainer", "rpc.Train")
    assert trains, "trainer rpc span missing from the trace"
    uploads = in_trace("scheduler", "train_upload")
    assert uploads
    fits = in_trace("trainer", "fit")
    assert fits, "fit span missing from the trace"
    assert {s.parent_id for s in fits} <= {s.span_id for s in trains}
