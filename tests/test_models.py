"""Model zoo: shapes, determinism, gradient flow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dragonfly2_tpu.models.attention import apply_transformer, init_transformer
from dragonfly2_tpu.models.gnn import (
    apply_graphsage,
    forward_edge_rtt,
    init_graphsage,
    predict_edge,
)
from dragonfly2_tpu.models.gru import apply_gru, init_gru, predict_next_cost
from dragonfly2_tpu.models.mlp import apply_mlp, init_mlp, score_parents


class TestMLP:
    def test_shapes_and_dtype(self):
        params = init_mlp(jax.random.PRNGKey(0), [12, 32, 1])
        x = jnp.ones((7, 12))
        out = apply_mlp(params, x)
        assert out.shape == (7, 1)
        assert out.dtype == jnp.float32
        assert score_parents(params, x).shape == (7,)

    def test_batch_rank_polymorphic(self):
        params = init_mlp(jax.random.PRNGKey(0), [12, 16, 1])
        x = jnp.ones((3, 20, 12))
        assert score_parents(params, x).shape == (3, 20)

    def test_grad_flows(self):
        params = init_mlp(jax.random.PRNGKey(0), [4, 8, 1])
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
        g = jax.grad(lambda p: jnp.mean(score_parents(p, x) ** 2))(params)
        norms = [float(jnp.abs(l["w"]).sum()) for l in g["layers"]]
        assert all(n > 0 for n in norms)


class TestGraphSAGE:
    def _graph(self, n=10, k=3, f=7):
        key = jax.random.PRNGKey(0)
        feats = jax.random.normal(key, (n, f))
        nbrs = jax.random.randint(jax.random.PRNGKey(1), (n, k), 0, n)
        mask = jnp.ones((n, k), jnp.float32)
        return feats, nbrs, mask

    def test_embeddings_normalized(self):
        feats, nbrs, mask = self._graph()
        params = init_graphsage(jax.random.PRNGKey(2), 7, [16, 8])
        emb = apply_graphsage(params, feats, nbrs, mask)
        assert emb.shape == (10, 8)
        norms = jnp.linalg.norm(emb, axis=-1)
        np.testing.assert_allclose(np.asarray(norms), 1.0, atol=1e-3)

    def test_edge_prediction(self):
        feats, nbrs, mask = self._graph()
        params = init_graphsage(jax.random.PRNGKey(2), 7, [16, 8])
        src = jnp.array([0, 1, 2], jnp.int32)
        dst = jnp.array([3, 4, 5], jnp.int32)
        pred = forward_edge_rtt(params, feats, nbrs, mask, src, dst)
        assert pred.shape == (3,)
        # direction matters: head sees ordered (src, dst)
        rev = forward_edge_rtt(params, feats, nbrs, mask, dst, src)
        assert not np.allclose(np.asarray(pred), np.asarray(rev))

    def test_isolated_node_stable(self):
        feats, nbrs, mask = self._graph()
        mask = mask.at[0].set(0.0)  # node 0 has no in-neighbors
        params = init_graphsage(jax.random.PRNGKey(2), 7, [16, 8])
        emb = apply_graphsage(params, feats, nbrs, mask)
        assert bool(jnp.all(jnp.isfinite(emb)))


class TestGRU:
    def test_shapes(self):
        params = init_gru(jax.random.PRNGKey(0), in_dim=5, hidden_dim=12)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 9, 5))
        hs, final = apply_gru(params, x)
        assert hs.shape == (4, 9, 12)
        assert final.shape == (4, 12)
        np.testing.assert_allclose(np.asarray(hs[:, -1]), np.asarray(final))
        assert predict_next_cost(params, x).shape == (4,)

    def test_length_masking(self):
        params = init_gru(jax.random.PRNGKey(0), in_dim=3, hidden_dim=8)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 3))
        lengths = jnp.array([3, 6])
        _, final = apply_gru(params, x, lengths)
        # short sequence's final state == state at its true last step
        _, final_trunc = apply_gru(params, x[:1, :3])
        np.testing.assert_allclose(np.asarray(final[0]), np.asarray(final_trunc[0]), atol=1e-6)


class TestTransformer:
    def test_forward(self):
        params = init_transformer(
            jax.random.PRNGKey(0), in_dim=6, model_dim=32, num_heads=4, num_layers=2
        )
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 6))
        out = apply_transformer(params, x)
        assert out.shape == (2, 16, 32)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_causality(self):
        params = init_transformer(
            jax.random.PRNGKey(0), in_dim=4, model_dim=16, num_heads=2, num_layers=1
        )
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 12, 4))
        base = apply_transformer(params, x, causal=True)
        x2 = x.at[0, -1].set(99.0)  # perturb the last step
        out2 = apply_transformer(params, x2, causal=True)
        # earlier positions unchanged under causal masking
        np.testing.assert_allclose(
            np.asarray(base[0, :-1]), np.asarray(out2[0, :-1]), atol=1e-5
        )
        assert not np.allclose(np.asarray(base[0, -1]), np.asarray(out2[0, -1]))
