"""Property tests for the candidate-parent filter rules (SURVEY §7's
'faithful scheduling semantics' hard part; reference
scheduling.go:500-571). Seeded-random swarms instead of hand-picked
fixtures: every invariant must hold on EVERY candidate list the filter
produces, across hundreds of generated states — the shape the reference's
1,830-line table-driven scheduling_test.go approximates by enumeration."""

import numpy as np
import pytest

from dragonfly2_tpu.scheduler import resource as res
from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
from dragonfly2_tpu.schema.records import Network

STATE_EVENTS = {
    # reachable feed states for a would-be parent
    "received": (res.PEER_EVENT_REGISTER_NORMAL,),
    "running-fed": (res.PEER_EVENT_REGISTER_NORMAL, res.PEER_EVENT_DOWNLOAD),
    "back-source": (
        res.PEER_EVENT_REGISTER_NORMAL,
        res.PEER_EVENT_DOWNLOAD_BACK_TO_SOURCE,
    ),
    "succeeded": (
        res.PEER_EVENT_REGISTER_NORMAL,
        res.PEER_EVENT_DOWNLOAD_BACK_TO_SOURCE,
        res.PEER_EVENT_DOWNLOAD_SUCCEEDED,
    ),
    "failed": (
        res.PEER_EVENT_REGISTER_NORMAL,
        res.PEER_EVENT_DOWNLOAD,
        res.PEER_EVENT_DOWNLOAD_FAILED,
    ),
}


def random_swarm(rng: np.random.Generator, n_peers: int):
    """A task with a random peer population: mixed states, host types,
    upload capacities, some shared hosts, some random DAG edges."""
    task = res.Task(f"task-{rng.integers(1e9)}", "https://origin/x")
    task.total_piece_count = int(rng.integers(1, 64))
    hosts = []
    for i in range(max(2, n_peers // 2)):
        h = res.Host(
            id=f"host-{i}",
            type=res.HostType.SUPER if rng.random() < 0.25 else res.HostType.NORMAL,
            hostname=f"h{i}",
            ip=f"10.0.0.{i}",
            port=8002,
            download_port=8001,
            concurrent_upload_limit=int(rng.integers(0, 4)),
        )
        h.concurrent_upload_count = int(rng.integers(0, 4))
        h.network = Network(idc=f"idc-{rng.integers(2)}", location="as|cn|sh")
        hosts.append(h)

    peers = []
    states = list(STATE_EVENTS.values())
    for i in range(n_peers):
        host = hosts[int(rng.integers(len(hosts)))]
        p = res.Peer(f"peer-{i}", task, host)
        task.store_peer(p)
        host.store_peer(p)
        for ev in states[int(rng.integers(len(states)))]:
            p.fsm.event(ev)
        peers.append(p)

    # random feasible parent→child edges among the population
    for _ in range(int(rng.integers(0, n_peers))):
        a, b = rng.integers(len(peers), size=2)
        pa, pb = peers[int(a)], peers[int(b)]
        if pa.id != pb.id and task.can_add_peer_edge(pa.id, pb.id):
            task.add_peer_edge(pa, pb)
    return task, peers


@pytest.mark.parametrize("seed", range(30))
def test_filter_invariants_hold_on_random_swarms(seed):
    rng = np.random.default_rng(seed)
    scheduling = Scheduling(BaseEvaluator(), SchedulingConfig())
    task, peers = random_swarm(rng, n_peers=int(rng.integers(4, 24)))

    for child in peers:
        if not child.fsm.is_state(
            res.PEER_STATE_RECEIVED_NORMAL, res.PEER_STATE_RUNNING
        ):
            continue
        blocklist = {
            peers[int(j)].id for j in rng.integers(len(peers), size=2)
        }
        child.block_parents.add(peers[int(rng.integers(len(peers)))].id)
        candidates, found = scheduling.find_candidate_parents(child, blocklist)
        assert found == bool(candidates)
        assert len(candidates) <= scheduling._candidate_parent_limit()
        seen = set()
        for cand in candidates:
            # rule 1-2: blocklists respected
            assert cand.id not in blocklist
            assert cand.id not in child.block_parents
            # rule 3: never the same host (self-feeding daemons)
            assert cand.host.id != child.host.id
            # rule 4: DAG stays acyclic — the edge must still be addable
            # (filter re-ran the check; adding must not create a cycle)
            assert task.can_add_peer_edge(cand.id, child.id)
            # rule 5: bad nodes excluded
            assert not scheduling.evaluator.is_bad_node(cand)
            # rule 6: unfed normal-host parents excluded
            if (
                cand.host.type is res.HostType.NORMAL
                and task.peer_in_degree(cand.id) == 0
            ):
                assert cand.fsm.is_state(
                    res.PEER_STATE_BACK_TO_SOURCE, res.PEER_STATE_SUCCEEDED
                )
            # rule 7: upload slots free
            assert cand.host.free_upload_count() > 0
            # no duplicates
            assert cand.id not in seen
            seen.add(cand.id)


@pytest.mark.parametrize("seed", range(10))
def test_evaluator_orders_candidates_by_score(seed):
    """The returned list is ranked: scores are non-increasing (the
    schedule response's first parent is the best one)."""
    rng = np.random.default_rng(100 + seed)
    scheduling = Scheduling(BaseEvaluator(), SchedulingConfig())
    task, peers = random_swarm(rng, n_peers=16)
    child = next(
        (
            p
            for p in peers
            if p.fsm.is_state(res.PEER_STATE_RECEIVED_NORMAL, res.PEER_STATE_RUNNING)
        ),
        None,
    )
    if child is None:
        pytest.skip("no schedulable child in this swarm")
    candidates, found = scheduling.find_candidate_parents(child)
    if not found:
        pytest.skip("no candidates in this swarm")
    ev = scheduling.evaluator
    total = task.total_piece_count
    scores = [ev.evaluate(c, child, total) for c in candidates]
    assert scores == sorted(scores, reverse=True)


@pytest.mark.parametrize("seed", range(10))
def test_schedule_edges_applied_are_acyclic(seed):
    """After repeated rescheduling across the whole swarm, the per-task
    peer DAG never holds a cycle (the invariant can_add_peer_edge
    protects; property-checked end-to-end here)."""
    rng = np.random.default_rng(200 + seed)
    scheduling = Scheduling(BaseEvaluator(), SchedulingConfig())
    task, peers = random_swarm(rng, n_peers=12)
    for child in peers:
        if not child.fsm.is_state(
            res.PEER_STATE_RECEIVED_NORMAL, res.PEER_STATE_RUNNING
        ):
            continue
        candidates, found = scheduling.find_candidate_parents(child)
        if found:
            task.delete_peer_in_edges(child.id)
            for cand in candidates:
                if task.can_add_peer_edge(cand.id, child.id):
                    task.add_peer_edge(cand, child)
    # walk the DAG: DFS from every node must terminate without revisiting
    # the path (utils.dag raises on cycles at insert; verify independently)
    graph = {p.id: set() for p in peers}
    for p in peers:
        for parent in task.peer_parents(p.id):  # → Peer objects
            graph[parent.id].add(p.id)

    def dfs(node, path):
        assert node not in path, f"cycle through {node}"
        for nxt in graph.get(node, ()):
            dfs(nxt, path | {node})

    for p in peers:
        dfs(p.id, set())
