"""Seed-peer mode + async jobs (preheat / sync_peers).

Seed flow (reference scheduler/resource/seed_peer.go): a cold task with
no parents triggers a seed download on a seed-type host; waiting children
then pull from the seed over P2P without touching the origin themselves.

Job flow (reference internal/job + scheduler/job): manager queues jobs,
the scheduler worker leases them over gRPC, executes, posts results.
"""

import json
import os
import time

import pytest

from dragonfly2_tpu.client import dfget
from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
from dragonfly2_tpu.client.piece_manager import TRAFFIC_REMOTE_PEER
from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.models_registry import ModelRegistry
from dragonfly2_tpu.manager.objectstorage import FSObjectStorage
from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.rpc import glue
from dragonfly2_tpu.rpc.glue import MANAGER_SERVICE, SCHEDULER_SERVICE, serve
from dragonfly2_tpu.scheduler import resource as res
from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
from dragonfly2_tpu.scheduler.job import JobWorker
from dragonfly2_tpu.scheduler.resource.seed_peer import SeedPeerClient
from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
from dragonfly2_tpu.scheduler.service import SchedulerService
from dragonfly2_tpu.scheduler.storage import Storage

import manager_pb2  # noqa: E402

PIECE = 32 * 1024
PAYLOAD = os.urandom(3 * PIECE)


@pytest.fixture
def seed_cluster(tmp_path):
    """Scheduler (seed-aware) + a seed daemon + a normal daemon."""
    resource = res.Resource()
    seed_client = SeedPeerClient(resource.host_manager)
    storage = Storage(tmp_path / "sched", buffer_size=1)
    service = SchedulerService(
        resource,
        Scheduling(
            BaseEvaluator(),
            # keep retrying while the seed downloads; never push the child
            # to the origin
            SchedulingConfig(
                retry_interval=0.1, retry_limit=100, retry_back_to_source_limit=100
            ),
            seed_client=seed_client,
        ),
        storage=storage,
    )
    server, port = serve({SCHEDULER_SERVICE: service})
    sched_addr = f"127.0.0.1:{port}"

    daemons = {}
    for name, host_type in (("seed", "super"), ("child", "normal")):
        d = Daemon(
            DaemonConfig(
                data_dir=str(tmp_path / f"daemon-{name}"),
                scheduler_address=sched_addr,
                hostname=f"host-{name}",
                ip="127.0.0.1",
                host_type=host_type,
                piece_length=PIECE,
                schedule_timeout=20.0,
                announce_interval=60.0,
            )
        )
        d.start()
        daemons[name] = d

    origin = tmp_path / "origin.bin"
    origin.write_bytes(PAYLOAD)

    yield {
        "resource": resource,
        "seed_client": seed_client,
        "daemons": daemons,
        "url": f"file://{origin}",
        "tmp": tmp_path,
    }
    for d in daemons.values():
        d.stop()
    server.stop(0)


def test_cold_task_is_seeded_not_back_to_source(seed_cluster):
    """Child downloads a cold task: the seed fetches the origin, the
    child pulls everything from the seed over P2P."""
    child = seed_cluster["daemons"]["child"]
    seed = seed_cluster["daemons"]["seed"]
    url = seed_cluster["url"]
    out = seed_cluster["tmp"] / "out.bin"

    assert len(seed_cluster["seed_client"].seed_hosts()) == 1

    dfget.download(f"127.0.0.1:{child.port}", url, str(out))
    assert out.read_bytes() == PAYLOAD

    task_id = child.task_manager.task_id_for(url, None)
    ts_child = child.storage.find_completed_task(task_id)
    traffic = {p.traffic_type for p in ts_child.meta.pieces.values()}
    assert traffic == {TRAFFIC_REMOTE_PEER}, f"child must not hit origin, got {traffic}"

    ts_seed = seed.storage.find_completed_task(task_id)
    assert ts_seed is not None, "seed daemon must hold the task"
    parents = {p.parent_id for p in ts_child.meta.pieces.values()}
    assert parents == {ts_seed.meta.peer_id}


@pytest.fixture
def manager_env(tmp_path):
    db = Database(tmp_path / "manager.db")
    cluster_id = db.ensure_default_cluster()
    models = ModelRegistry(db, FSObjectStorage(tmp_path / "objects"))
    service = ManagerService(db, models)
    server, port = serve({MANAGER_SERVICE: service})
    channel = glue.dial(f"127.0.0.1:{port}")
    client = glue.ServiceClient(channel, MANAGER_SERVICE)
    yield {"client": client, "db": db, "cluster_id": cluster_id}
    channel.close()
    server.stop(0)


def test_job_queue_roundtrip(manager_env):
    client = manager_env["client"]
    job = client.CreateJob(
        manager_pb2.CreateJobRequest(type="sync_peers", args_json="{}")
    )
    assert job.state == "queued"

    resource = res.Resource()
    resource.host_manager.store(res.Host(id="h1", hostname="a", ip="1.2.3.4"))
    worker = JobWorker(client, resource, hostname="sched", ip="127.0.0.1")
    n = worker.poll_once()
    assert n == 1

    done = client.GetJob(manager_pb2.GetJobRequest(id=job.id))
    assert done.state == "succeeded"
    result = json.loads(done.result_json)
    assert result["hosts"][0]["id"] == "h1"

    # leased jobs aren't handed out twice
    assert worker.poll_once() == 0


def test_preheat_job_triggers_seed(manager_env, seed_cluster):
    client = manager_env["client"]
    url = seed_cluster["url"]
    job = client.CreateJob(
        manager_pb2.CreateJobRequest(
            type="preheat", args_json=json.dumps({"urls": [url]})
        )
    )
    worker = JobWorker(
        client,
        seed_cluster["resource"],
        seed_client=seed_cluster["seed_client"],
        hostname="sched",
        ip="127.0.0.1",
    )
    assert worker.poll_once() == 1
    done = client.GetJob(manager_pb2.GetJobRequest(id=job.id))
    assert done.state == "succeeded"
    assert json.loads(done.result_json)["count"] == 1

    # the seed daemon ends up holding the task without any child download
    seed = seed_cluster["daemons"]["seed"]
    task_id = seed.task_manager.task_id_for(url, None)
    deadline = time.time() + 15
    while time.time() < deadline:
        if seed.storage.find_completed_task(task_id) is not None:
            break
        time.sleep(0.2)
    ts = seed.storage.find_completed_task(task_id)
    assert ts is not None and len(ts.meta.pieces) == 3


def test_unknown_job_type_rejected(manager_env):
    import grpc

    with pytest.raises(grpc.RpcError):
        manager_env["client"].CreateJob(manager_pb2.CreateJobRequest(type="nope"))


def test_preheat_without_seeds_fails(manager_env):
    client = manager_env["client"]
    job = client.CreateJob(
        manager_pb2.CreateJobRequest(
            type="preheat", args_json=json.dumps({"urls": ["file:///x"]})
        )
    )
    worker = JobWorker(
        client,
        res.Resource(),
        seed_client=SeedPeerClient(res.Resource().host_manager),
        hostname="s",
        ip="1.1.1.1",
    )
    worker.poll_once()
    done = client.GetJob(manager_pb2.GetJobRequest(id=job.id))
    assert done.state == "failed"
    assert "no seed peers" in json.loads(done.result_json)["error"]


def test_stale_lease_result_rejected(manager_env):
    """A worker that lost its lease cannot clobber the re-leased worker's
    outcome."""
    import grpc

    client = manager_env["client"]
    job = client.CreateJob(manager_pb2.CreateJobRequest(type="sync_peers"))
    # worker A leases...
    client.ListPendingJobs(
        manager_pb2.ListPendingJobsRequest(hostname="a", ip="1.1.1.1")
    )
    # ...but worker B posts with a different identity → rejected
    with pytest.raises(grpc.RpcError) as exc_info:
        client.UpdateJobResult(
            manager_pb2.UpdateJobResultRequest(
                id=job.id, state="succeeded", result_json="{}",
                hostname="b", ip="2.2.2.2",
            )
        )
    assert exc_info.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    # the rightful leaseholder's post lands
    done = client.UpdateJobResult(
        manager_pb2.UpdateJobResultRequest(
            id=job.id, state="succeeded", result_json="{}",
            hostname="a", ip="1.1.1.1",
        )
    )
    assert done.state == "succeeded"


class _RefusingSeedClient:
    """Seed-client double whose triggers refuse after ``accept`` urls —
    the shape JobWorker._preheat must account for honestly."""

    def __init__(self, accept: int = 0):
        self.accept = accept
        self.calls = 0
        self.triggered = []

    def seed_hosts(self):
        return ["seed-host"]

    def trigger(self, task_id, url, **kw):
        self.calls += 1
        self.triggered.append((task_id, url, kw))
        return self.calls <= self.accept


def test_preheat_zero_triggered_reports_failed():
    """Every seed trigger refused → the job is FAILED, not a green
    result with count 0 (the silent-failure bug this release fixes)."""
    worker = JobWorker(None, res.Resource(), seed_client=_RefusingSeedClient(0))
    state, result = worker.execute_now(
        "preheat", {"urls": ["file:///a", "file:///b", "file:///c"]}
    )
    assert state == "failed"
    assert result["count"] == 0
    assert result["failed"] == 3
    assert "0 of 3 urls triggered" in result["error"]


def test_preheat_partial_success_reports_failed_count():
    """Partial trigger success stays succeeded but says how many of N
    were refused, so operators see the gap without diffing url lists."""
    worker = JobWorker(None, res.Resource(), seed_client=_RefusingSeedClient(2))
    state, result = worker.execute_now(
        "preheat", {"urls": ["file:///a", "file:///b", "file:///c"]}
    )
    assert state == "succeeded"
    assert result["count"] == 2
    assert result["failed"] == 1
    assert len(result["triggered"]) == 2
    assert "error" not in result


def test_preheat_task_specs_trigger_demanded_identity():
    """The planner's per-task specs: an explicit task_id (the id the
    demand was observed under) and per-url meta ride through to the seed
    trigger verbatim — the job must never recompute a different identity
    from job-level tag/application."""
    from dragonfly2_tpu.utils.idgen import URLMeta, task_id_v1

    seed = _RefusingSeedClient(2)
    worker = JobWorker(None, res.Resource(), seed_client=seed)
    demanded = task_id_v1("file:///a", URLMeta(tag="ml"))
    state, result = worker.execute_now(
        "preheat",
        {
            "tasks": [
                {"task_id": demanded, "url": "file:///a", "tag": "ml"},
                {"url": "file:///b", "tag": "reg", "application": "pull"},
            ],
            # job-level meta must NOT leak into per-task triggers
            "tag": "planner-private",
        },
    )
    assert state == "succeeded"
    assert result["count"] == 2 and result["failed"] == 0
    tid_a, url_a, kw_a = seed.triggered[0]
    assert tid_a == demanded and url_a == "file:///a" and kw_a["tag"] == "ml"
    tid_b, _, kw_b = seed.triggered[1]
    # no explicit id: derived from the entry's own url + meta, exactly
    # as the seed daemon will derive it
    assert tid_b == task_id_v1("file:///b", URLMeta(tag="reg", application="pull"))
    assert kw_b["tag"] == "reg" and kw_b["application"] == "pull"


def test_preheat_empty_args_is_a_distinct_failure():
    """Zero urls is a malformed job ('no urls in job args'), distinct
    from N urls all refusing to trigger ('0 of N urls triggered')."""
    worker = JobWorker(None, res.Resource(), seed_client=_RefusingSeedClient(0))
    state, result = worker.execute_now("preheat", {"urls": []})
    assert state == "failed"
    assert result["error"] == "no urls in job args"


def test_execute_now_runs_inline_without_manager():
    """The planner's managerless path: execute_now dispatches through
    the same _execute the leased path runs."""
    resource = res.Resource()
    resource.host_manager.store(res.Host(id="h9", hostname="a", ip="9.9.9.9"))
    worker = JobWorker(None, resource)
    state, result = worker.execute_now("sync_peers", {})
    assert state == "succeeded"
    assert result["hosts"][0]["id"] == "h9"
    state, result = worker.execute_now("nope", {})
    assert state == "failed"
