"""RPC plane integration: real gRPC on localhost — AnnouncePeer bidi
scheduling, SyncProbes, host announce/leave, and the announcer→trainer
Train stream firing an actual fit."""

import queue
import threading
import time

import numpy as np
import pytest

import grpc

from dragonfly2_tpu.rpc import gen  # noqa: F401
import common_pb2
import scheduler_pb2
import trainer_pb2

from dragonfly2_tpu.rpc.glue import (
    SERVICES,
    ConsistentHashRing,
    ServiceClient,
    dial,
    serve,
)
from dragonfly2_tpu.scheduler import resource as res
from dragonfly2_tpu.scheduler.announcer import Announcer
from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
from dragonfly2_tpu.scheduler.networktopology import NetworkTopology
from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
from dragonfly2_tpu.scheduler.service import SERVICE_NAME as SCHED_SERVICE
from dragonfly2_tpu.scheduler.service import SchedulerService
from dragonfly2_tpu.scheduler.storage import Storage
from dragonfly2_tpu.trainer.service import SERVICE_NAME as TRAINER_SERVICE
from dragonfly2_tpu.trainer.service import TrainerService
from dragonfly2_tpu.trainer.storage import TrainerStorage
from dragonfly2_tpu.trainer.train import FitConfig, GNNFitConfig
from dragonfly2_tpu.trainer.training import Training, TrainingConfig
from dragonfly2_tpu.utils.kvstore import KVStore


class StreamDriver:
    """Queue-driven bidi client: push requests, read responses."""

    def __init__(self, call_fn):
        self._q = queue.Queue()
        self._responses = call_fn(iter(self._q.get, None))

    def send(self, req):
        self._q.put(req)

    def close(self):
        self._q.put(None)

    def recv(self, timeout=5.0):
        out = {}

        def read():
            try:
                out["resp"] = next(self._responses)
            except StopIteration:
                out["resp"] = None

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(timeout)
        if "resp" not in out:
            raise TimeoutError("no response within timeout")
        return out["resp"]


def wait_until(cond, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def make_host_info(i, seed=False):
    return common_pb2.HostInfo(
        id=f"host-{i}",
        type="super" if seed else "normal",
        hostname=f"h{i}",
        ip=f"10.0.0.{i}",
        port=8002,
        download_port=8001,
        concurrent_upload_limit=50,
        network=common_pb2.NetworkStat(idc="idc-a", location="as|cn|sh|dc1"),
    )


@pytest.fixture
def cluster(tmp_path):
    resource = res.Resource()
    storage = Storage(tmp_path / "sched", buffer_size=1)
    nt = NetworkTopology(KVStore(), resource.host_manager, storage)
    service = SchedulerService(
        resource,
        Scheduling(BaseEvaluator(), SchedulingConfig(retry_interval=0.0, retry_back_to_source_limit=1)),
        storage=storage,
        networktopology=nt,
    )
    server, port = serve({SCHED_SERVICE: service})
    channel = dial(f"127.0.0.1:{port}")
    client = ServiceClient(channel, SCHED_SERVICE)
    yield resource, storage, nt, client, service
    channel.close()
    server.stop(0)


def register_and_run_seed(client, task_id="task-1"):
    """Seed peer registers, goes back-to-source, finishes all pieces."""
    client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=make_host_info(0, seed=True)))
    d = StreamDriver(client.AnnouncePeer)
    d.send(
        scheduler_pb2.AnnouncePeerRequest(
            host_id="host-0",
            task_id=task_id,
            peer_id="seed-peer",
            register_peer=scheduler_pb2.RegisterPeerRequest(
                task_id=task_id, peer_id="seed-peer", url="https://origin/blob"
            ),
        )
    )
    resp = d.recv()  # unknown size → normal register → no parents → back-to-source
    assert resp.WhichOneof("response") == "need_back_to_source"
    d.send(
        scheduler_pb2.AnnouncePeerRequest(
            host_id="host-0", task_id=task_id, peer_id="seed-peer",
            download_peer_back_to_source_started=scheduler_pb2.DownloadPeerBackToSourceStartedRequest(),
        )
    )
    for n in range(8):
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-0", task_id=task_id, peer_id="seed-peer",
                download_piece_finished=scheduler_pb2.DownloadPieceFinishedRequest(
                    piece=common_pb2.PieceInfo(
                        number=n, parent_id="", offset=n << 20, length=1 << 20,
                        traffic_type="back_to_source", cost_ns=int(5e6),
                    )
                ),
            )
        )
    d.send(
        scheduler_pb2.AnnouncePeerRequest(
            host_id="host-0", task_id=task_id, peer_id="seed-peer",
            download_peer_finished=scheduler_pb2.DownloadPeerFinishedRequest(
                content_length=8 << 20, piece_count=8, cost_ns=int(1e9)
            ),
        )
    )
    return d


class TestAnnouncePeer:
    def test_schedule_child_off_seed(self, cluster):
        resource, storage, nt, client, _ = cluster
        seed_stream = register_and_run_seed(client)
        assert wait_until(
            lambda: (p := resource.peer_manager.load("seed-peer")) is not None
            and p.fsm.current == "Succeeded"
        )
        # scheduler needs task piece metadata for scope; set after seed run
        task = resource.task_manager.load("task-1")
        task.total_piece_count = 8

        client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=make_host_info(1)))
        d = StreamDriver(client.AnnouncePeer)
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-1", task_id="task-1", peer_id="child-1",
                register_peer=scheduler_pb2.RegisterPeerRequest(
                    task_id="task-1", peer_id="child-1", url="https://origin/blob"
                ),
            )
        )
        resp = d.recv()
        assert resp.WhichOneof("response") == "normal_task"
        parents = resp.normal_task.candidate_parents
        assert [p.peer_id for p in parents] == ["seed-peer"]
        assert parents[0].host.download_port == 8001
        assert list(parents[0].finished_pieces) == list(range(8))

        # piece events then completion → download record written
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-1", task_id="task-1", peer_id="child-1",
                download_peer_started=scheduler_pb2.DownloadPeerStartedRequest(),
            )
        )
        for n in range(8):
            d.send(
                scheduler_pb2.AnnouncePeerRequest(
                    host_id="host-1", task_id="task-1", peer_id="child-1",
                    download_piece_finished=scheduler_pb2.DownloadPieceFinishedRequest(
                        piece=common_pb2.PieceInfo(
                            number=n, parent_id="seed-peer", offset=n << 20,
                            length=1 << 20, traffic_type="remote_peer", cost_ns=int(12e6),
                        )
                    ),
                )
            )
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-1", task_id="task-1", peer_id="child-1",
                download_peer_finished=scheduler_pb2.DownloadPeerFinishedRequest(
                    content_length=8 << 20, piece_count=8, cost_ns=int(2e9)
                ),
            )
        )
        d.close()
        seed_stream.close()

        def child_record_written():
            storage.flush()
            return any(r.id == "child-1" for r in storage.list_download())

        assert wait_until(child_record_written)
        child_recs = [r for r in storage.list_download() if r.id == "child-1"]
        assert len(child_recs) == 1
        assert child_recs[0].parents[0].id == "seed-peer"
        assert len(child_recs[0].parents[0].pieces) == 8
        # upload outcome accounting reached the seed host
        assert resource.host_manager.load("host-0").upload_count == 8

    def test_reschedule_blocks_parent(self, cluster):
        resource, storage, nt, client, _ = cluster
        seed_stream = register_and_run_seed(client)
        assert wait_until(
            lambda: (p := resource.peer_manager.load("seed-peer")) is not None
            and p.fsm.current == "Succeeded"
        )
        resource.task_manager.load("task-1").total_piece_count = 8
        client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=make_host_info(1)))
        d = StreamDriver(client.AnnouncePeer)
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-1", task_id="task-1", peer_id="child-1",
                register_peer=scheduler_pb2.RegisterPeerRequest(
                    task_id="task-1", peer_id="child-1", url="https://origin/blob"
                ),
            )
        )
        assert d.recv().WhichOneof("response") == "normal_task"
        # block the only parent → reschedule must fall to back-to-source
        d.send(
            scheduler_pb2.AnnouncePeerRequest(
                host_id="host-1", task_id="task-1", peer_id="child-1",
                reschedule=scheduler_pb2.RescheduleRequest(blocked_parent_ids=["seed-peer"]),
            )
        )
        resp = d.recv()
        assert resp.WhichOneof("response") == "need_back_to_source"
        d.close()
        seed_stream.close()

    def test_stat_and_leave(self, cluster):
        resource, _, _, client, _ = cluster
        seed_stream = register_and_run_seed(client)
        assert wait_until(
            lambda: (p := resource.peer_manager.load("seed-peer")) is not None
            and p.fsm.current == "Succeeded"
        )
        stat = client.StatPeer(scheduler_pb2.StatPeerRequest(task_id="task-1", peer_id="seed-peer"))
        assert stat.state == "Succeeded"
        assert stat.finished_piece_count == 8
        task_stat = client.StatTask(scheduler_pb2.StatTaskRequest(task_id="task-1"))
        assert task_stat.has_available_peer
        client.LeavePeer(scheduler_pb2.LeavePeerRequest(task_id="task-1", peer_id="seed-peer"))
        assert resource.peer_manager.load("seed-peer").fsm.current == "Leave"
        with pytest.raises(grpc.RpcError):
            client.StatPeer(scheduler_pb2.StatPeerRequest(task_id="task-1", peer_id="ghost"))
        seed_stream.close()

    def test_leave_host_purges_topology(self, cluster):
        resource, _, nt, client, _ = cluster
        client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=make_host_info(5)))
        from dragonfly2_tpu.scheduler.networktopology import Probe

        nt.enqueue_probe("host-5", Probe("host-0", rtt_ns=1000))
        client.LeaveHost(scheduler_pb2.LeaveHostRequest(host_id="host-5"))
        assert resource.host_manager.load("host-5") is None
        assert not nt.has_edge("host-5", "host-0")


class TestSyncProbes:
    def test_probe_round(self, cluster):
        resource, _, nt, client, _ = cluster
        for i in range(6):
            client.AnnounceHost(scheduler_pb2.AnnounceHostRequest(host=make_host_info(i)))
        d = StreamDriver(client.SyncProbes)
        d.send(
            scheduler_pb2.SyncProbesRequest(
                host=make_host_info(0),
                probe_started=scheduler_pb2.ProbeStartedRequest(),
            )
        )
        resp = d.recv()
        targets = [h.host.id for h in resp.hosts]
        assert 0 < len(targets) <= 5 and "host-0" not in targets
        d.send(
            scheduler_pb2.SyncProbesRequest(
                host=make_host_info(0),
                probe_finished=scheduler_pb2.ProbeFinishedRequest(
                    probes=[
                        scheduler_pb2.ProbeResult(host_id=t, rtt_ns=int(3e6))
                        for t in targets
                    ]
                ),
            )
        )
        d.close()
        assert wait_until(lambda: nt.average_rtt("host-0", targets[0]) == int(3e6))


class TestTrainStream:
    def test_announcer_upload_triggers_training(self, tmp_path):
        from dragonfly2_tpu.schema import synth
        from dragonfly2_tpu.schema.columnar import write_csv

        # scheduler side: storage with datasets
        sched_storage = Storage(tmp_path / "sched", buffer_size=1)
        for r in synth.make_download_records(100, seed=1):
            sched_storage.create_download(r)
        for r in synth.make_topology_records(300, num_hosts=24, seed=2):
            sched_storage.create_network_topology(r)
        sched_storage.flush()

        # trainer side: real service, synchronous fit, recording manager
        class RecordingManager:
            def __init__(self):
                self.models = {}

            def create_model(self, model_id, model_type, ip, hostname, params, evaluation):
                self.models[model_type] = evaluation

        manager = RecordingManager()
        t_storage = TrainerStorage(tmp_path / "trainer")
        training = Training(
            t_storage,
            manager,
            TrainingConfig(
                mlp=FitConfig(hidden_dims=(16,), batch_size=128, epochs=3, seed=0),
                gnn=GNNFitConfig(hidden_dims=(16,), batch_size=256, epochs=60, learning_rate=3e-2, seed=0),
            ),
        )
        service = TrainerService(t_storage, training, synchronous=True)
        server, port = serve({TRAINER_SERVICE: service})
        channel = dial(f"127.0.0.1:{port}")

        ann = Announcer(
            sched_storage,
            ip="10.1.1.1",
            hostname="sched-A",
            trainer_channel=channel,
            upload_chunk=1 << 16,  # small chunks to exercise chunking
        )
        assert ann.train_once()
        # gru included: third family trains under production defaults (round 5)
        assert set(manager.models) == {"mlp", "gnn", "gru"}
        assert manager.models["mlp"]["mse"] > 0
        assert manager.models["gnn"]["f1"] > 0
        # scheduler's local datasets cleared after upload
        assert sched_storage.list_download() == []
        channel.close()
        server.stop(0)


class TestConsistentHash:
    def test_stable_assignment(self):
        ring = ConsistentHashRing(["s1:8002", "s2:8002", "s3:8002"])
        picks = {f"task-{i}": ring.pick(f"task-{i}") for i in range(50)}
        assert all(ring.pick(k) == v for k, v in picks.items())  # stable
        assert len(set(picks.values())) > 1  # spreads

    def test_remove_moves_only_affected(self):
        ring = ConsistentHashRing(["s1", "s2", "s3"])
        before = {f"t{i}": ring.pick(f"t{i}") for i in range(100)}
        ring.remove("s2")
        after = {k: ring.pick(k) for k in before}
        moved = [k for k in before if before[k] != after[k]]
        assert all(before[k] == "s2" for k in moved)  # only s2's keys moved
        assert all(v != "s2" for v in after.values())

    def test_empty_ring_raises(self):
        with pytest.raises(ValueError):
            ConsistentHashRing().pick("t")
