"""Security plane: cert issuing (utils/issuer, reference pkg/issuer),
TLS/mTLS gRPC (rpc/glue), and the proxy's HTTPS MITM interception
(reference client/daemon/proxy/proxy.go:268-766 cert spoofing)."""

import os
import ssl
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.utils.issuer import CertificateAuthority, SpoofingIssuer


# ---------------------------------------------------------------------------
# issuer
# ---------------------------------------------------------------------------


def test_ca_issues_verifiable_leaf(tmp_path):
    from cryptography import x509
    from cryptography.hazmat.primitives.asymmetric import padding

    ca = CertificateAuthority()
    pair = ca.issue("svc.example", hosts=["svc.example", "127.0.0.1"])
    leaf = x509.load_pem_x509_certificate(pair.cert_pem)
    root = x509.load_pem_x509_certificate(ca.cert_pem)
    # signed by the CA
    root.public_key().verify(
        leaf.signature, leaf.tbs_certificate_bytes,
        padding.PKCS1v15(), leaf.signature_hash_algorithm,
    )
    sans = leaf.extensions.get_extension_for_class(
        x509.SubjectAlternativeName
    ).value
    assert "svc.example" in sans.get_values_for_type(x509.DNSName)

    # round-trips through PEM load
    ca2 = CertificateAuthority.load(ca.cert_pem, ca.key_pem)
    assert ca2.cert_pem == ca.cert_pem


def test_spoofing_issuer_caches_per_host():
    issuer = SpoofingIssuer(CertificateAuthority())
    a1 = issuer.for_host("registry.example")
    a2 = issuer.for_host("registry.example")
    b = issuer.for_host("other.example")
    assert a1 is a2
    assert b is not a1


# ---------------------------------------------------------------------------
# TLS gRPC
# ---------------------------------------------------------------------------


def _tls_scheduler(tmp_path, client_ca=None):
    from dragonfly2_tpu.rpc.glue import serve
    from dragonfly2_tpu.scheduler import resource as res
    from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
    from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
    from dragonfly2_tpu.scheduler.service import SERVICE_NAME, SchedulerService

    ca = CertificateAuthority()
    pair = ca.issue("scheduler.local", hosts=["scheduler.local", "127.0.0.1"])
    resource = res.Resource()
    service = SchedulerService(
        resource, Scheduling(BaseEvaluator(), SchedulingConfig())
    )
    server, port = serve(
        {SERVICE_NAME: service},
        tls=(pair.key_pem, pair.cert_pem),
        client_ca=client_ca,
    )
    return ca, resource, server, port


def test_grpc_tls_roundtrip(tmp_path):
    from dragonfly2_tpu.rpc import gen  # noqa: F401
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc.glue import SCHEDULER_SERVICE, ServiceClient, dial

    ca, resource, server, port = _tls_scheduler(tmp_path)
    try:
        ch = dial(
            f"127.0.0.1:{port}",
            tls_ca=ca.cert_pem,
            tls_server_name="scheduler.local",
        )
        client = ServiceClient(ch, SCHEDULER_SERVICE)
        client.AnnounceHost(
            scheduler_pb2.AnnounceHostRequest(
                host=common_pb2.HostInfo(id="h-tls", ip="10.0.0.1", port=1)
            )
        )
        assert resource.host_manager.load("h-tls") is not None
        ch.close()

        # a client trusting a DIFFERENT root must fail the handshake
        other = CertificateAuthority()
        with pytest.raises(ConnectionError):
            dial(
                f"127.0.0.1:{port}",
                retries=1,
                tls_ca=other.cert_pem,
                tls_server_name="scheduler.local",
            )
    finally:
        server.stop(0)


def test_grpc_mtls_requires_client_cert(tmp_path):
    from dragonfly2_tpu.rpc import gen  # noqa: F401
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc.glue import SCHEDULER_SERVICE, ServiceClient, dial

    client_ca = CertificateAuthority("client CA")
    ca, resource, server, port = _tls_scheduler(tmp_path, client_ca=client_ca.cert_pem)
    try:
        # without a client cert the handshake is rejected
        with pytest.raises(ConnectionError):
            dial(
                f"127.0.0.1:{port}",
                retries=1,
                tls_ca=ca.cert_pem,
                tls_server_name="scheduler.local",
            )
        # with an issued client pair it works
        cpair = client_ca.issue("daemon-1")
        ch = dial(
            f"127.0.0.1:{port}",
            tls_ca=ca.cert_pem,
            tls_client=(cpair.key_pem, cpair.cert_pem),
            tls_server_name="scheduler.local",
        )
        client = ServiceClient(ch, SCHEDULER_SERVICE)
        client.AnnounceHost(
            scheduler_pb2.AnnounceHostRequest(
                host=common_pb2.HostInfo(id="h-mtls", ip="10.0.0.2", port=1)
            )
        )
        assert resource.host_manager.load("h-mtls") is not None
        ch.close()
    finally:
        server.stop(0)


# ---------------------------------------------------------------------------
# HTTPS MITM proxy
# ---------------------------------------------------------------------------


def test_proxy_mitm_intercepts_https(tmp_path, monkeypatch):
    """An HTTPS origin behind the MITM proxy: the client CONNECTs, gets
    the spoofed cert (trusting the proxy CA), and the decrypted GET is
    served through the P2P transport (direct route here) with correct
    bytes."""
    from dragonfly2_tpu.client.proxy import ProxyServer
    from dragonfly2_tpu.client.transport import P2PTransport

    payload = os.urandom(48 * 1024)

    # HTTPS origin with a cert from its own CA
    origin_ca = CertificateAuthority("origin CA")
    opair = origin_ca.issue("127.0.0.1", hosts=["127.0.0.1"])

    class Origin(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Content-Type", "application/octet-stream")
            self.end_headers()
            self.wfile.write(payload)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Origin)
    octx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ocert = tmp_path / "origin.crt"
    okey = tmp_path / "origin.key"
    ocert.write_bytes(opair.cert_pem)
    okey.write_bytes(opair.key_pem)
    octx.load_cert_chain(str(ocert), str(okey))
    httpd.socket = octx.wrap_socket(httpd.socket, server_side=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    origin_port = httpd.server_address[1]

    # upstream fetches must trust the origin's CA — DF_ORIGIN_CA is the
    # product knob for origins behind a private CA
    ca_file = tmp_path / "origin-ca.crt"
    ca_file.write_bytes(origin_ca.cert_pem)
    monkeypatch.setenv("DF_ORIGIN_CA", str(ca_file))

    # MITM proxy with its own spoofing CA
    proxy_ca = CertificateAuthority("proxy CA")
    proxy = ProxyServer(
        P2PTransport(None, rules=[]),  # no rules -> direct route
        issuer=SpoofingIssuer(proxy_ca),
    )
    proxy.start()
    try:
        # client trusts the PROXY CA (the spoofed leaf must verify)
        proxy_ca_file = tmp_path / "proxy-ca.crt"
        proxy_ca_file.write_bytes(proxy_ca.cert_pem)
        client_ctx = ssl.create_default_context(cafile=str(proxy_ca_file))
        opener = urllib.request.build_opener(
            urllib.request.ProxyHandler(
                {"https": f"http://127.0.0.1:{proxy.port}"}
            ),
            urllib.request.HTTPSHandler(context=client_ctx),
        )
        with opener.open(
            f"https://127.0.0.1:{origin_port}/blob/layer1", timeout=15
        ) as resp:
            body = resp.read()
            assert resp.headers.get("X-Dragonfly-Via-P2P") is not None
        assert body == payload
    finally:
        proxy.stop()
        httpd.shutdown()
        httpd.server_close()


def test_mitm_forwards_non_get_methods(tmp_path, monkeypatch):
    """docker-push-style POST through an intercepted host must reach the
    origin, not die with 501."""
    from dragonfly2_tpu.client.proxy import ProxyServer
    from dragonfly2_tpu.client.transport import P2PTransport

    origin_ca = CertificateAuthority("origin CA")
    opair = origin_ca.issue("127.0.0.1", hosts=["127.0.0.1"])
    got = {}

    class Origin(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            got["body"] = self.rfile.read(n)
            got["path"] = self.path
            self.send_response(202)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Origin)
    octx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    (tmp_path / "o.crt").write_bytes(opair.cert_pem)
    (tmp_path / "o.key").write_bytes(opair.key_pem)
    octx.load_cert_chain(str(tmp_path / "o.crt"), str(tmp_path / "o.key"))
    httpd.socket = octx.wrap_socket(httpd.socket, server_side=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    (tmp_path / "oca.crt").write_bytes(origin_ca.cert_pem)
    monkeypatch.setenv("DF_ORIGIN_CA", str(tmp_path / "oca.crt"))

    proxy_ca = CertificateAuthority("proxy CA")
    proxy = ProxyServer(P2PTransport(None, rules=[]), issuer=SpoofingIssuer(proxy_ca))
    proxy.start()
    try:
        (tmp_path / "pca.crt").write_bytes(proxy_ca.cert_pem)
        ctx = ssl.create_default_context(cafile=str(tmp_path / "pca.crt"))
        opener = urllib.request.build_opener(
            urllib.request.ProxyHandler({"https": f"http://127.0.0.1:{proxy.port}"}),
            urllib.request.HTTPSHandler(context=ctx),
        )
        req = urllib.request.Request(
            f"https://127.0.0.1:{httpd.server_address[1]}/v2/blobs/uploads/",
            data=b"layerdata",
            method="POST",
        )
        with opener.open(req, timeout=15) as resp:
            assert resp.status == 202
            assert resp.read() == b"ok"
        assert got["body"] == b"layerdata"
        assert got["path"] == "/v2/blobs/uploads/"

        # chunked upload (docker PATCH blob): decoded and forwarded
        # whole, keep-alive stays in sync for the follow-up request
        import http.client

        conn = http.client.HTTPSConnection(
            "127.0.0.1", httpd.server_address[1], context=ctx, timeout=15
        )
        conn.host, conn.port = "127.0.0.1", proxy.port  # CONNECT via proxy
        conn.set_tunnel("127.0.0.1", httpd.server_address[1])
        conn.request(
            "POST", "/v2/blobs/uploads/", body=iter([b"chun", b"ked-", b"body"])
        )  # http.client sends iterables chunked
        r = conn.getresponse()
        assert r.status == 202 and r.read() == b"ok"
        assert got["body"] == b"chunked-body"
        # same tunnel, next request — desync would garble this one
        conn.request("POST", "/v2/blobs/uploads/", body=b"after")
        r = conn.getresponse()
        assert r.status == 202 and r.read() == b"ok"
        assert got["body"] == b"after"
        conn.close()
    finally:
        proxy.stop()
        httpd.shutdown()
        httpd.server_close()


def test_scheduler_server_tls_via_config(tmp_path):
    """The scheduler ASSEMBLY serves TLS from config file paths and a
    TLS client (trusting the CA) can announce."""
    from dragonfly2_tpu.rpc import gen  # noqa: F401
    import common_pb2
    import scheduler_pb2

    from dragonfly2_tpu.rpc.glue import SCHEDULER_SERVICE, ServiceClient, dial
    from dragonfly2_tpu.scheduler.server import SchedulerServer, SchedulerServerConfig

    ca = CertificateAuthority()
    pair = ca.issue("scheduler.local", hosts=["scheduler.local", "127.0.0.1"])
    cert_f = tmp_path / "s.crt"
    key_f = tmp_path / "s.key"
    cert_f.write_bytes(pair.cert_pem)
    key_f.write_bytes(pair.key_pem)

    server = SchedulerServer(
        SchedulerServerConfig(
            data_dir=str(tmp_path / "data"),
            tls_cert_file=str(cert_f),
            tls_key_file=str(key_f),
        )
    )
    addr = server.serve()
    try:
        ch = dial(addr, tls_ca=ca.cert_pem, tls_server_name="scheduler.local")
        client = ServiceClient(ch, SCHEDULER_SERVICE)
        client.AnnounceHost(
            scheduler_pb2.AnnounceHostRequest(
                host=common_pb2.HostInfo(id="h-cfg-tls", ip="10.0.0.3", port=1)
            )
        )
        assert server.resource.host_manager.load("h-cfg-tls") is not None
        ch.close()
    finally:
        server.stop()


def test_daemon_dials_tls_scheduler_via_config(tmp_path):
    """Config-only TLS cluster: scheduler serves TLS, the daemon trusts
    the CA via scheduler_tls_ca_file, and a real download completes."""
    from dragonfly2_tpu.client import dfget
    from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
    from dragonfly2_tpu.scheduler.server import SchedulerServer, SchedulerServerConfig

    ca = CertificateAuthority()
    pair = ca.issue("scheduler.local", hosts=["scheduler.local", "127.0.0.1"])
    for name, blob in (("s.crt", pair.cert_pem), ("s.key", pair.key_pem),
                       ("ca.crt", ca.cert_pem)):
        (tmp_path / name).write_bytes(blob)

    server = SchedulerServer(
        SchedulerServerConfig(
            data_dir=str(tmp_path / "sched"),
            tls_cert_file=str(tmp_path / "s.crt"),
            tls_key_file=str(tmp_path / "s.key"),
            retry_interval=0.0,
        )
    )
    addr = server.serve()
    d = Daemon(
        DaemonConfig(
            data_dir=str(tmp_path / "daemon"),
            scheduler_address=addr,
            scheduler_tls_ca_file=str(tmp_path / "ca.crt"),
            scheduler_tls_server_name="scheduler.local",
            hostname="host-tls",
            piece_length=32 * 1024,
            announce_interval=60.0,
        )
    )
    d.start()
    try:
        payload = os.urandom(64 * 1024)
        origin = tmp_path / "o.bin"
        origin.write_bytes(payload)
        out = tmp_path / "out.bin"
        dfget.download(f"127.0.0.1:{d.port}", f"file://{origin}", str(out))
        assert out.read_bytes() == payload
        assert server.resource.host_manager.load(d.host_id) is not None
    finally:
        d.stop()
        server.stop()


def test_partial_tls_config_fails_loudly():
    from dragonfly2_tpu.rpc.glue import serve_tls_args

    with pytest.raises(ValueError, match="incomplete"):
        serve_tls_args(client_ca_file="/tmp/ca.pem")
    with pytest.raises(ValueError, match="incomplete"):
        serve_tls_args(cert_file="/tmp/c.pem")
    assert serve_tls_args() == {}


def test_mtls_cluster_via_config(tmp_path):
    """mTLS end-to-end through config: the scheduler requires client
    certs; the daemon presents an issued pair and completes a download."""
    from dragonfly2_tpu.client import dfget
    from dragonfly2_tpu.client.daemon import Daemon, DaemonConfig
    from dragonfly2_tpu.scheduler.server import SchedulerServer, SchedulerServerConfig

    server_ca = CertificateAuthority("server CA")
    client_ca = CertificateAuthority("client CA")
    spair = server_ca.issue("scheduler.local", hosts=["scheduler.local", "127.0.0.1"])
    cpair = client_ca.issue("daemon-mtls")
    files = {
        "s.crt": spair.cert_pem, "s.key": spair.key_pem,
        "server-ca.crt": server_ca.cert_pem, "client-ca.crt": client_ca.cert_pem,
        "c.crt": cpair.cert_pem, "c.key": cpair.key_pem,
    }
    for name, blob in files.items():
        (tmp_path / name).write_bytes(blob)

    server = SchedulerServer(
        SchedulerServerConfig(
            data_dir=str(tmp_path / "sched"),
            tls_cert_file=str(tmp_path / "s.crt"),
            tls_key_file=str(tmp_path / "s.key"),
            tls_client_ca_file=str(tmp_path / "client-ca.crt"),
        )
    )
    addr = server.serve()
    d = Daemon(
        DaemonConfig(
            data_dir=str(tmp_path / "daemon"),
            scheduler_address=addr,
            scheduler_tls_ca_file=str(tmp_path / "server-ca.crt"),
            scheduler_tls_server_name="scheduler.local",
            scheduler_tls_client_cert_file=str(tmp_path / "c.crt"),
            scheduler_tls_client_key_file=str(tmp_path / "c.key"),
            hostname="host-mtls",
            piece_length=32 * 1024,
            announce_interval=60.0,
        )
    )
    d.start()
    try:
        payload = os.urandom(48 * 1024)
        origin = tmp_path / "o.bin"
        origin.write_bytes(payload)
        out = tmp_path / "out.bin"
        dfget.download(f"127.0.0.1:{d.port}", f"file://{origin}", str(out))
        assert out.read_bytes() == payload
    finally:
        d.stop()
        server.stop()
