"""Dynconfig engine: cache, disk fallback, observers, service wrappers."""

import json
import time

import pytest

from dragonfly2_tpu.utils.dynconfig import Dynconfig, SchedulerDynconfig


def test_caches_within_interval():
    calls = []

    def fetch():
        calls.append(1)
        return {"v": len(calls)}

    dc = Dynconfig(fetch, refresh_interval=60.0)
    assert dc.get() == {"v": 1}
    assert dc.get() == {"v": 1}  # cached — no second fetch
    assert len(calls) == 1


def test_refresh_after_expiry():
    calls = []

    def fetch():
        calls.append(1)
        return {"v": len(calls)}

    dc = Dynconfig(fetch, refresh_interval=0.0)
    assert dc.get() == {"v": 1}
    assert dc.get() == {"v": 2}


def test_fetch_failure_falls_back_to_memory_then_disk(tmp_path):
    cache = tmp_path / "dyn.json"
    state = {"fail": False}

    def fetch():
        if state["fail"]:
            raise ConnectionError("manager down")
        return {"limit": 7}

    dc = Dynconfig(fetch, cache_path=cache, refresh_interval=0.0)
    assert dc.get() == {"limit": 7}
    assert json.loads(cache.read_text()) == {"limit": 7}  # mirrored to disk

    state["fail"] = True
    assert dc.get() == {"limit": 7}  # previous value survives the outage

    # a fresh process with a dead manager boots from the disk cache
    dc2 = Dynconfig(fetch, cache_path=cache, refresh_interval=0.0)
    assert dc2.get() == {"limit": 7}


def test_observer_fires_on_change_only():
    values = [{"a": 1}, {"a": 1}, {"a": 2}]
    it = iter(values)
    seen = []

    dc = Dynconfig(lambda: next(it), refresh_interval=0.0)
    dc.register(seen.append)
    dc.refresh()
    dc.refresh()  # same data — no notify
    dc.refresh()
    assert seen == [{"a": 1}, {"a": 2}]


def test_register_delivers_current_data():
    dc = Dynconfig(lambda: {"x": 1}, refresh_interval=60.0)
    dc.refresh()
    seen = []
    dc.register(seen.append)
    assert seen == [{"x": 1}]


def test_background_refresh_loop():
    calls = []
    dc = Dynconfig(lambda: calls.append(1) or {"n": len(calls)}, refresh_interval=0.05)
    dc.start()
    try:
        deadline = time.time() + 2
        while len(calls) < 3 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        dc.stop()
    assert len(calls) >= 3


def test_scheduler_dynconfig_feeds_scheduling(tmp_path):
    """End to end: manager cluster config → SchedulerDynconfig →
    Scheduling's live candidate limit."""
    from dragonfly2_tpu.manager.database import Database
    from dragonfly2_tpu.manager.models_registry import ModelRegistry
    from dragonfly2_tpu.manager.objectstorage import FSObjectStorage
    from dragonfly2_tpu.manager.service import ManagerService
    from dragonfly2_tpu.rpc import glue
    from dragonfly2_tpu.rpc.glue import MANAGER_SERVICE, serve
    from dragonfly2_tpu.scheduler.evaluator import BaseEvaluator
    from dragonfly2_tpu.scheduler.scheduling import Scheduling

    db = Database(tmp_path / "m.db")
    cluster_id = db.ensure_default_cluster()
    db.execute(
        "UPDATE scheduler_clusters SET config = ? WHERE id = ?",
        (json.dumps({"candidate_parent_limit": 9, "filter_parent_limit": 33}), cluster_id),
    )
    service = ManagerService(db, ModelRegistry(db, FSObjectStorage(tmp_path / "obj")))
    server, port = serve({MANAGER_SERVICE: service})
    channel = glue.dial(f"127.0.0.1:{port}")
    try:
        client = glue.ServiceClient(channel, MANAGER_SERVICE)
        dyn = SchedulerDynconfig(
            client, cluster_id=cluster_id, cache_path=tmp_path / "dyn.json",
            refresh_interval=0.0,
        )
        assert dyn.candidate_parent_limit == 9
        assert dyn.filter_parent_limit == 33

        scheduling = Scheduling(BaseEvaluator(), dynconfig=dyn)
        assert scheduling._candidate_parent_limit() == 9
        assert scheduling._filter_parent_limit() == 33

        # live update: operator changes the cluster config
        db.execute(
            "UPDATE scheduler_clusters SET config = ? WHERE id = ?",
            (json.dumps({"candidate_parent_limit": 2}), cluster_id),
        )
        assert scheduling._candidate_parent_limit() == 2
    finally:
        channel.close()
        server.stop(0)


def test_daemon_dynconfig_scheduler_list(tmp_path):
    from dragonfly2_tpu.manager.database import Database
    from dragonfly2_tpu.manager.models_registry import ModelRegistry
    from dragonfly2_tpu.manager.objectstorage import FSObjectStorage
    from dragonfly2_tpu.manager.service import ManagerService
    from dragonfly2_tpu.rpc import glue
    from dragonfly2_tpu.rpc.glue import MANAGER_SERVICE, serve
    from dragonfly2_tpu.utils.dynconfig import DaemonDynconfig

    import manager_pb2  # noqa: E402

    db = Database(tmp_path / "m.db")
    service = ManagerService(db, ModelRegistry(db, FSObjectStorage(tmp_path / "obj")))
    server, port = serve({MANAGER_SERVICE: service})
    channel = glue.dial(f"127.0.0.1:{port}")
    try:
        client = glue.ServiceClient(channel, MANAGER_SERVICE)
        client.UpdateScheduler(
            manager_pb2.UpdateSchedulerRequest(hostname="s1", ip="10.0.0.1", port=7001)
        )
        dyn = DaemonDynconfig(client, refresh_interval=0.0)
        assert dyn.scheduler_addresses() == ["10.0.0.1:7001"]
    finally:
        channel.close()
        server.stop(0)
