"""hack/dfanalyze — the framework stays green on the real package and
each pass actually catches the defect class it exists for: a planted
ABBA cycle (the PR 2 shape), a blocking call under a lock, a hot-path
function-local import, a plain-Lock self-deadlock — plus the runtime
lock-witness detecting a real inverted acquisition order from a thread,
the allowlist discipline (suppression, staleness, mandatory comments),
and the mypy-baseline machinery exercised without mypy installed."""

import json
import threading
from pathlib import Path

import pytest

from hack import dfanalyze
from hack.dfanalyze import jitwitness, witness
from hack.dfanalyze.passes import blocking, hygiene, jaxhygiene, lockorder, typecheck

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# the tier-1 wiring: the real package must analyze clean
# ---------------------------------------------------------------------------


def test_repo_is_clean():
    report = dfanalyze.run()
    failures = [
        f"{f['pass']}: {f['file']}:{f['line']}: {f['message']}"
        for p in report["passes"]
        for f in p["findings"]
        if not f["allowlisted"]
    ]
    failures += report["summary"]["stale_allowlist"]
    failures += report["summary"]["allowlist_errors"]
    assert report["ok"], "\n".join(failures)


def test_every_allowlist_entry_has_a_comment():
    al = dfanalyze.Allowlist.load()
    assert al.errors == []
    assert al.entries, "allowlist should carry the audited exceptions"
    assert all(c.strip() for c in al.entries.values())


# ---------------------------------------------------------------------------
# planted-defect fixtures
# ---------------------------------------------------------------------------


@pytest.fixture
def fakepkg(tmp_path):
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    return pkg


ABBA_FIXTURE = '''
import threading

class Engine:
    def __init__(self):
        self._lock = threading.RLock()
        self._flush_lock = threading.Lock()

    def flush(self):
        with self._flush_lock:
            with self._lock:
                pass

    def export(self):
        # the PR 2 bug shape: flush() takes _flush_lock while _lock is
        # already held -> inverts flush's _flush_lock -> _lock order
        with self._lock:
            return self.flush()
'''


def test_lockorder_catches_the_pr2_abba_shape(fakepkg):
    (fakepkg / "engine.py").write_text(ABBA_FIXTURE)
    res = lockorder.run(fakepkg)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert len(cycles) == 1
    msg = cycles[0].message
    assert "Engine._flush_lock" in msg and "Engine._lock" in msg
    assert "via flush()" in msg or "via Engine.flush()" in msg


def test_lockorder_catches_plain_lock_reentry(fakepkg):
    (fakepkg / "re.py").write_text(
        """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def _helper(self):
        with self._lock:
            pass

    def outer(self):
        with self._lock:
            self._helper()
"""
    )
    res = lockorder.run(fakepkg)
    assert any(f.key.startswith("self:") for f in res.findings)


def test_lockorder_ignores_rlock_reentry(fakepkg):
    (fakepkg / "re.py").write_text(
        """
import threading

class S:
    def __init__(self):
        self._lock = threading.RLock()

    def _helper(self):
        with self._lock:
            pass

    def outer(self):
        with self._lock:
            self._helper()
"""
    )
    res = lockorder.run(fakepkg)
    assert res.findings == []


FLEET_SHAPE_FIXTURE = '''
import threading

class Membership:
    """The scheduler/fleet.py shape: KV I/O strictly OUTSIDE the lock,
    ring mutation + owner checks under it, never nesting into a second
    lock."""

    def __init__(self, kv, ring):
        self._lock = threading.Lock()
        self.kv = kv
        self.ring = ring
        self._members = ()

    def reconcile(self):
        members = tuple(self.kv.scan_iter("fleet:member:*"))  # outside
        with self._lock:
            self._members = members

    def check_owner(self, task_id):
        with self._lock:
            return self.ring.pick(task_id)


class Selector:
    """The glue.SchedulerSelector shape: the ring lock releases BEFORE
    the dial — no call chain ever holds Membership._lock and
    Selector._lock together."""

    def __init__(self, membership):
        self._lock = threading.Lock()
        self.membership = membership

    def resolve(self, task_id):
        with self._lock:
            candidates = list(self._ring_candidates(task_id))
        return candidates[0]

    def _ring_candidates(self, task_id):
        return [task_id]
'''


def test_lockorder_fleet_shape_is_clean(fakepkg):
    """The fleet's lock model (Membership._lock, Selector._lock — KV
    I/O outside, no nesting between the two) must analyze clean; this
    fixture documents the intended shape so a regression that nests
    them shows up against a named baseline."""
    (fakepkg / "fleet.py").write_text(FLEET_SHAPE_FIXTURE)
    res = lockorder.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_lockorder_catches_a_fleet_nesting_regression(fakepkg):
    """The defect the clean shape guards against: a reconcile that
    calls into the selector while holding the membership lock, while
    the selector's refresh calls back into membership under its own
    lock — the ABBA the fleet plane must never grow."""
    (fakepkg / "fleet_bad.py").write_text(
        '''
import threading

class BadFleet:
    def __init__(self):
        self._lock = threading.Lock()       # membership state
        self._ring_lock = threading.Lock()  # selector ring

    def reconcile(self):
        with self._lock:
            self._push_ring()  # membership -> ring

    def _push_ring(self):
        with self._ring_lock:
            pass

    def resolve(self):
        with self._ring_lock:
            self._owner()  # ring -> membership: the inversion

    def _owner(self):
        with self._lock:
            pass
'''
    )
    res = lockorder.run(fakepkg)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert cycles, [f.message for f in res.findings]
    assert "BadFleet._lock" in cycles[0].message
    assert "BadFleet._ring_lock" in cycles[0].message


WAVE_PACK_SHAPE_FIXTURE = '''
import threading

class Topo:
    """The engine side of the wave join: ONE lock hold snapshots the
    host index, the gather kernel dispatches AFTER release."""

    def __init__(self):
        self._lock = threading.RLock()

    def rtt_affinity_pairs(self):
        with self._lock:
            snap = 1  # index/edges/D snapshot only
        return snap  # kernel dispatch outside the lock


class WaveEvaluator:
    """The evaluator side: pack (topology lock inside, released before
    scoring), then rung notes under _rung_lock — no chain ever holds
    Topo._lock and WaveEvaluator._rung_lock together."""

    def __init__(self, topo):
        self._rung_lock = threading.Lock()
        self.topo = topo

    def evaluate_wave(self):
        feats = self.topo.rtt_affinity_pairs()
        self._note_rung()
        return feats

    def _note_rung(self):
        with self._rung_lock:
            pass
'''


def test_lockorder_wave_pack_shape_is_clean(fakepkg):
    """The wave-pack lock model (ISSUE 16): the topology snapshot lock
    releases before the gather dispatch and before any rung-note lock —
    this fixture names the intended shape so a nesting regression shows
    up against a baseline."""
    (fakepkg / "wave.py").write_text(WAVE_PACK_SHAPE_FIXTURE)
    res = lockorder.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_lockorder_catches_a_wave_pack_nesting_regression(fakepkg):
    """The defect the clean shape guards against: a pack that gathers
    UNDER the rung lock while a topology callback notes the rung under
    its own lock — the ABBA the wave plane must never grow."""
    (fakepkg / "wave_bad.py").write_text(
        '''
import threading

class BadWave:
    def __init__(self):
        self._rung_lock = threading.Lock()
        self._topo_lock = threading.Lock()

    def evaluate_wave(self):
        with self._rung_lock:
            self._gather()  # rung -> topo: pack under the rung lock

    def _gather(self):
        with self._topo_lock:
            pass

    def on_flush(self):
        with self._topo_lock:
            self._note_rung()  # topo -> rung: the inversion

    def _note_rung(self):
        with self._rung_lock:
            pass
'''
    )
    res = lockorder.run(fakepkg)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert cycles, [f.message for f in res.findings]
    assert "BadWave._rung_lock" in cycles[0].message
    assert "BadWave._topo_lock" in cycles[0].message


REPLICATION_SHAPE_FIXTURE = '''
import threading

class Ledger:
    """The scheduler/swarm.py shape: every observatory hook is one
    short hold on the ledger lock; nothing under it calls out of the
    module."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dirty = set()

    def on_piece(self, tid):
        with self._lock:
            self._dirty.add(tid)

    def drain_dirty(self):
        with self._lock:
            drained = set(self._dirty)
            self._dirty.clear()
            return drained

    def export_task(self, tid):
        with self._lock:
            return {"id": tid}


class Replicator:
    """The scheduler/swarm_replication.py shape: every ledger call
    happens OUTSIDE the replicator lock — the dirty drain before the
    hold, the payload exports after release — so the two locks never
    nest in either direction."""

    def __init__(self, ledger):
        self._lock = threading.Lock()
        self.ledger = ledger
        self._pending = {}

    def flush_once(self):
        dirty = self.ledger.drain_dirty()  # ledger lock, alone
        with self._lock:  # replicator lock, alone
            for tid in dirty:
                self._pending[tid] = None
            batch = list(self._pending)
            self._pending.clear()
        return [self.ledger.export_task(t) for t in batch]
'''


def test_lockorder_replication_shape_is_clean(fakepkg):
    """The replication plane's lock model (ISSUE 20): the replicator
    drains the observatory's dirty set before taking its own lock and
    exports payloads after releasing it, so Replicator._lock and
    Ledger._lock never nest — this fixture names the intended shape so
    a regression that nests them shows up against a baseline."""
    (fakepkg / "replication.py").write_text(REPLICATION_SHAPE_FIXTURE)
    res = lockorder.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_lockorder_catches_a_replication_nesting_regression(fakepkg):
    """The defect the clean shape guards against: a flush that exports
    UNDER the replicator lock while an observatory hook notifies the
    replicator under the ledger lock — the ABBA the one-way
    replicator→ledger rule forbids."""
    (fakepkg / "replication_bad.py").write_text(
        '''
import threading

class BadReplicator:
    def __init__(self):
        self._lock = threading.Lock()         # replicator backlog
        self._ledger_lock = threading.Lock()  # observatory ledger

    def flush_once(self):
        with self._lock:
            self._export()  # replicator -> ledger: export under the hold

    def _export(self):
        with self._ledger_lock:
            pass

    def on_piece(self):
        with self._ledger_lock:
            self._mark_dirty()  # ledger -> replicator: the inversion

    def _mark_dirty(self):
        with self._lock:
            pass
'''
    )
    res = lockorder.run(fakepkg)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert cycles, [f.message for f in res.findings]
    assert "BadReplicator._lock" in cycles[0].message
    assert "BadReplicator._ledger_lock" in cycles[0].message


def test_blocking_catches_calls_under_lock(fakepkg):
    (fakepkg / "svc.py").write_text(
        """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def sleepy(self):
        with self._lock:
            time.sleep(1.0)

    def _announce(self, stub):
        stub.AnnouncePeer(object())

    def rpc_under_lock(self, stub):
        with self._lock:
            self._announce(stub)

    def queue_under_lock(self, q):
        with self._lock:
            q.get(timeout=1.0)
"""
    )
    res = blocking.run(fakepkg)
    cats = {f.key.split(":")[-2] for f in res.findings}
    assert "sleep" in cats
    assert "rpc" in cats  # transitively, via _announce
    assert "queue" in cats
    # the transitive finding names the call chain
    assert any("via S._announce" in f.message for f in res.findings)


def test_hygiene_catches_hot_import_and_except_pass(fakepkg):
    (fakepkg / "hot.py").write_text(
        """# dfanalyze: hot

def hot_path():
    from fakepkg import helper
    return helper
"""
    )
    (fakepkg / "loopy.py").write_text(
        """
def churn(items):
    for it in items:
        try:
            it.work()
        except Exception:
            pass
"""
    )
    res = hygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "import:fakepkg/hot.py:hot_path:fakepkg" in keys
    assert any(k.startswith("except-pass:fakepkg/loopy.py:churn") for k in keys)


def test_hygiene_catches_discarded_contextvar_token(fakepkg):
    (fakepkg / "cv.py").write_text(
        """
import contextvars

_current = contextvars.ContextVar("c", default=None)

def leak(value):
    _current.set(value)
"""
    )
    res = hygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "contextvar:fakepkg/cv.py:_current:discarded" in keys
    assert "contextvar:fakepkg/cv.py:_current:noreset" in keys


def test_clean_module_has_no_findings(fakepkg):
    (fakepkg / "clean.py").write_text(
        """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def drain(self):
        with self._lock:
            out, self._items = self._items, []
        return out
"""
    )
    report = dfanalyze.run(package_dir=fakepkg, allowlist=dfanalyze.Allowlist())
    assert report["ok"], json.dumps(report["passes"], indent=2)


# ---------------------------------------------------------------------------
# jaxhygiene: planted fixtures for every finding kind
# ---------------------------------------------------------------------------


def test_jaxhygiene_catches_host_sync_and_side_effects_under_trace(fakepkg):
    (fakepkg / "traced.py").write_text(
        """
import jax
import numpy as np

@jax.jit
def bad_step(params, x):
    v = float(x)          # host sync under trace
    y = x.item()          # host sync under trace
    z = np.asarray(x)     # numpy pull mid-trace
    print(x)              # trace-time-only side effect
    return v + y + z
"""
    )
    res = jaxhygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "host-sync:fakepkg/traced.py:bad_step:float" in keys
    assert "host-sync:fakepkg/traced.py:bad_step:item" in keys
    assert "host-sync:fakepkg/traced.py:bad_step:np.asarray" in keys
    assert "side-effect:fakepkg/traced.py:bad_step:print" in keys


def test_jaxhygiene_catches_traced_branch_but_not_static_branch(fakepkg):
    (fakepkg / "branchy.py").write_text(
        """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("mode",))
def step(x, mode):
    if mode:        # static arg: legal python control flow
        x = x + 1
    if x > 0:       # traced value: crashes or bakes one branch in
        x = x * 2
    return x
"""
    )
    res = jaxhygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "traced-branch:fakepkg/branchy.py:step:x" in keys
    assert not any(k.endswith(":mode") for k in keys)


def test_jaxhygiene_catches_jit_in_loop(fakepkg):
    (fakepkg / "loopy.py").write_text(
        """
import jax

def churn(fns, xs):
    out = []
    for f, x in zip(fns, xs):
        out.append(jax.jit(f)(x))  # a compile per iteration
    return out
"""
    )
    res = jaxhygiene.run(fakepkg)
    assert any(f.key.startswith("jit-in-loop:fakepkg/loopy.py:churn") for f in res.findings)


def test_jaxhygiene_catches_jit_per_call_only_in_device_hot(fakepkg):
    src = """
import jax

def fwd(params, x):
    return x

def rank(params, feats):
    return jax.jit(fwd)(params, feats)  # fresh wrapper per rank() call
"""
    (fakepkg / "cold.py").write_text(src)
    (fakepkg / "hot.py").write_text("# dfanalyze: device-hot\n" + src)
    res = jaxhygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "jit-per-call:fakepkg/hot.py:rank" in keys
    assert not any("cold.py" in k for k in keys)


def test_jaxhygiene_memoized_factory_is_exempt(fakepkg):
    (fakepkg / "memo.py").write_text(
        """# dfanalyze: device-hot
import jax

_step_cache: dict = {}

def get_step(lr):
    if lr in _step_cache:
        return _step_cache[lr]

    @jax.jit
    def step(params, x):
        return params, x * lr

    _step_cache[lr] = step
    return step
"""
    )
    res = jaxhygiene.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_jaxhygiene_catches_unstable_static_args(fakepkg):
    (fakepkg / "statics.py").write_text(
        """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("shape", "opts"))
def build(x, shape, opts=[]):
    return x

def caller(x):
    return build(x, shape=[4, 4])  # a list never hits the jit cache
"""
    )
    res = jaxhygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert "unstable-static:fakepkg/statics.py:build:opts" in keys  # bad default
    assert "unstable-static:fakepkg/statics.py:build:shape" in keys  # bad call site


def test_jaxhygiene_catches_block_until_ready_and_host_pull(fakepkg):
    (fakepkg / "sync.py").write_text(
        """# dfanalyze: device-hot
import jax
import numpy as np

def wait_all(xs, arr, i):
    jax.block_until_ready(xs)
    return np.asarray(arr)[i]  # whole-array D2H to read one element
"""
    )
    res = jaxhygiene.run(fakepkg)
    keys = {f.key for f in res.findings}
    assert (
        "block-until-ready:fakepkg/sync.py:wait_all:jax.block_until_ready" in keys
    )
    assert "host-pull:fakepkg/sync.py:wait_all:np.asarray" in keys


def test_jaxhygiene_clean_device_hot_module(fakepkg):
    """The idioms the fixes in this PR converged on — module-scope jits,
    explicit boundary conversion, device-side indexing — analyze clean."""
    (fakepkg / "clean.py").write_text(
        """# dfanalyze: device-hot
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def step(params, x):
    return params, x * 2

def feed(params, buf):
    return step(params, jnp.asarray(buf))

def read_one(arr, i):
    return float(np.asarray(arr[i]))  # index on device, pull one element
"""
    )
    res = jaxhygiene.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_jaxhygiene_allowlist_suppresses_and_goes_stale(fakepkg, tmp_path):
    (fakepkg / "hot.py").write_text(
        """# dfanalyze: device-hot
import jax

def fwd(x):
    return x

def rank(feats):
    return jax.jit(fwd)(feats)
"""
    )
    key = "jit-per-call:fakepkg/hot.py:rank"
    al_file = tmp_path / "allow.txt"
    al_file.write_text(f"jaxhygiene {key}  # audited: test fixture\n")
    al = dfanalyze.Allowlist.load(al_file)
    report = dfanalyze.run(package_dir=fakepkg, allowlist=al)
    assert report["ok"], json.dumps(report["summary"], indent=2)
    assert report["summary"]["allowlisted"] == 1

    (fakepkg / "hot.py").write_text("x = 1\n")
    al2 = dfanalyze.Allowlist.load(al_file)
    report2 = dfanalyze.run(package_dir=fakepkg, allowlist=al2)
    assert not report2["ok"]
    assert report2["summary"]["stale_allowlist"] == [f"jaxhygiene {key}"]


def test_collect_jit_sites_and_device_hot_files(fakepkg):
    (fakepkg / "a.py").write_text(
        """# dfanalyze: device-hot
import jax

@jax.jit
def fwd(x):
    return x
"""
    )
    (fakepkg / "b.py").write_text("import jax\n\ndef g(x):\n    return x\n\nh = jax.jit(g)\n")
    sites = jaxhygiene.collect_jit_sites(fakepkg)
    assert "fwd" in sites and sites["fwd"][0][0] == "fakepkg/a.py"
    assert "g" in sites
    assert jaxhygiene.device_hot_files(fakepkg) == {"fakepkg/a.py"}


# ---------------------------------------------------------------------------
# allowlist discipline
# ---------------------------------------------------------------------------


def test_allowlist_suppresses_and_goes_stale(fakepkg, tmp_path):
    (fakepkg / "svc.py").write_text(
        """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def sleepy(self):
        with self._lock:
            time.sleep(1.0)
"""
    )
    key = "fakepkg/svc.py:S.sleepy:S._lock:sleep:time.sleep"
    al_file = tmp_path / "allow.txt"
    al_file.write_text(f"blocking {key}  # audited: test fixture\n")
    al = dfanalyze.Allowlist.load(al_file)
    report = dfanalyze.run(package_dir=fakepkg, allowlist=al)
    assert report["ok"]
    assert report["summary"]["allowlisted"] == 1

    # same allowlist against a now-clean package -> stale entry fails
    (fakepkg / "svc.py").write_text("x = 1\n")
    al2 = dfanalyze.Allowlist.load(al_file)
    report2 = dfanalyze.run(package_dir=fakepkg, allowlist=al2)
    assert not report2["ok"]
    assert report2["summary"]["stale_allowlist"] == [f"blocking {key}"]


def test_allowlist_requires_comment(tmp_path):
    f = tmp_path / "allow.txt"
    f.write_text("blocking some:key\n")
    al = dfanalyze.Allowlist.load(f)
    assert al.errors and "comment" in al.errors[0]


# ---------------------------------------------------------------------------
# runtime lock-witness
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_witness():
    """Install the witness scoped to THIS file's locks. Under a
    DF_LOCK_WITNESS=1 session the witness is already live package-wide
    (and uninstalling it here would blind the rest of the session), so
    these meta-tests skip — the session itself is the witness test."""
    if witness.active():
        pytest.skip("lock witness already active session-wide")
    witness.reset()
    witness.install(package_roots=("tests/",))
    yield
    witness.uninstall()
    witness.reset()


def test_witness_detects_inverted_order_from_a_thread(fresh_witness, fakepkg, tmp_path):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    snap = witness.snapshot()
    edges = {
        (e["from"].rsplit(":", 1)[-1], e["to"].rsplit(":", 1)[-1])
        for e in snap["edges"]
    }
    assert len(edges) >= 2  # both orders observed

    report = tmp_path / "witness.json"
    report.write_text(json.dumps(snap))
    res = lockorder.witness_crosscheck(fakepkg, report)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert cycles, [f.message for f in res.findings]
    assert "static+witnessed" in cycles[0].message


def test_witness_rlock_reentry_is_not_an_edge(fresh_witness):
    r = threading.RLock()
    with r:
        with r:  # re-entry, same instance: no order edge
            pass
    assert witness.snapshot()["edges"] == []


def test_witness_flags_cross_instance_nesting(fresh_witness, fakepkg, tmp_path):
    def make():
        return threading.Lock()  # ONE creation site, two instances

    l1, l2 = make(), make()
    with l1:
        with l2:
            pass
    snap = witness.snapshot()
    assert any(e["same_site"] for e in snap["edges"])
    report = tmp_path / "witness.json"
    report.write_text(json.dumps(snap))
    res = lockorder.witness_crosscheck(fakepkg, report)
    assert any(f.key.startswith("cross-instance:") for f in res.findings)


def test_witness_cross_thread_release_purges_held_stack(fresh_witness):
    """A Lock released by another thread (the hand-off pattern, legal
    for threading.Lock) must not linger on the acquirer's held-stack and
    mint phantom order pairs."""
    lk = threading.Lock()
    other = threading.Lock()
    lk.acquire()  # main thread holds lk...
    t = threading.Thread(target=lk.release)  # ...a worker releases it
    t.start()
    t.join()
    with other:  # next acquire must NOT record a bogus lk -> other pair
        pass
    assert witness.snapshot()["edges"] == []


def test_witness_ignores_stdlib_locks(fresh_witness):
    import queue

    q = queue.Queue()  # queue's internal lock is created in stdlib code
    q.put(1)
    assert q.get() == 1
    assert witness.snapshot()["locks"] == {}


def test_witness_lock_passes_as_real_lock(fresh_witness):
    """Condition/with duck-typing: the wrappers behave like the real
    primitives (non-blocking acquire, locked(), context manager)."""
    lk = threading.Lock()
    assert lk.acquire(False) is True
    assert lk.locked()
    assert lk.acquire(False) is False
    lk.release()
    cond = threading.Condition(threading.RLock())
    with cond:
        cond.notify_all()


# ---------------------------------------------------------------------------
# runtime jit witness
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_jitwitness():
    """Install the jit witness scoped to THIS file's jax usage. Under a
    DF_JIT_WITNESS=1 session the witness is already live package-wide
    (and uninstalling it would blind the rest of the session), so these
    meta-tests skip — the session itself is the witness test."""
    pytest.importorskip("jax")
    if jitwitness.active():
        pytest.skip("jit witness already active session-wide")
    jitwitness.reset()
    jitwitness.install(package_roots=("tests/",))
    yield
    jitwitness.uninstall()
    jitwitness.reset()


def test_jitwitness_records_compiles_rewraps_and_transfers(fresh_jitwitness):
    import numpy as np

    import jax

    def wfx_fn(x):
        return x * 2

    for i in range(3):
        fn = jax.jit(wfx_fn)  # fresh wrapper each round: 3 at one site
        fn(np.ones((2 + i,), np.float32))  # numpy in: implicit transfer
    snap = jitwitness.snapshot()
    assert snap["compiles"]["wfx_fn"]["count"] == 3
    assert len(snap["compiles"]["wfx_fn"]["signatures"]) == 3
    wfx_sites = [w for w in snap["wrapper_sites"] if w["target"] == "wfx_fn"]
    assert len(wfx_sites) == 1 and wfx_sites[0]["count"] == 3
    implicit = [t for t in snap["transfers"] if not t["explicit"]]
    assert implicit and implicit[0]["target"] == "wfx_fn"


def test_jitwitness_warm_cache_records_nothing_new(fresh_jitwitness):
    import jax
    import jax.numpy as jnp

    def wfy_fn(x):
        return x + 1

    fn = jax.jit(wfy_fn)
    x = jnp.ones((4,))
    fn(x)
    jitwitness.reset()  # past the warmup
    fn(x)  # cached executable, jax array in
    snap = jitwitness.snapshot()
    assert "wfy_fn" not in snap["compiles"]
    assert [t for t in snap["transfers"] if not t["explicit"]] == []


def test_jitwitness_device_put_is_explicit(fresh_jitwitness):
    import numpy as np

    import jax

    jax.device_put(np.ones((3,), np.float32))
    snap = jitwitness.snapshot()
    assert snap["transfers"] and all(t["explicit"] for t in snap["transfers"])


def test_jitwitness_roundtrip_crosscheck(fresh_jitwitness, fakepkg, tmp_path):
    """The full loop: real compiles/wrappers/transfers recorded here,
    dumped, then joined onto a planted static package whose jit site
    names match — retrace storm, wrapper churn, and the device-hot
    implicit transfer all surface as findings."""
    import numpy as np

    import jax

    def wfz_fn(x):
        return x * 3

    for i in range(jaxhygiene.MAX_SIGNATURES + 2):
        jax.jit(wfz_fn)(np.ones((2 + i,), np.float32))
    snap = jitwitness.snapshot()
    # the witnessed facts join onto the static package by function name
    # and device-hot file; rewrite the recorded sites onto the fixture
    (fakepkg / "plane.py").write_text(
        """# dfanalyze: device-hot
import jax

def wfz_fn(x):
    return x * 3

ranked = jax.jit(wfz_fn)
"""
    )
    snap["wrapper_sites"] = [
        {"site": "fakepkg/plane.py:7", "target": "wfz_fn", "count": 99}
    ]
    snap["transfers"] = [
        {
            "file": "fakepkg/plane.py",
            "fn": "rank",
            "line": 8,
            "target": "wfz_fn",
            "explicit": False,
            "count": 12,
        }
    ]
    report = tmp_path / "jit-witness.json"
    report.write_text(json.dumps(snap))
    res = jaxhygiene.witness_crosscheck(fakepkg, report)
    keys = {f.key for f in res.findings}
    assert "retrace:wfz_fn" in keys
    assert "jit-rewrap:fakepkg/plane.py:wfz_fn" in keys
    assert "transfer:fakepkg/plane.py:rank" in keys


def test_jitwitness_crosscheck_flags_packing_thread_transfer(fakepkg, tmp_path):
    """ISSUE 15 gate: an ingest.py transfer on any thread OTHER than the
    trainer.ingest-* stages fails the crosscheck regardless of
    explicitness or frame name — notably the realistic regression where
    `put(arg)` moves back into the packing loop (fn is still "put", but
    the thread is the caller's). The sanctioned stage threads and the
    named post-stream tail functions stay clean."""
    dump = {
        "compiles": {},
        "wrapper_sites": [],
        "transfers": [
            {  # inline device work in the packing body
                "file": "dragonfly2_tpu/trainer/ingest.py",
                "fn": "stream_train_mlp",
                "line": 700,
                "target": "device_put",
                "explicit": True,
                "thread": "MainThread",
                "count": 3,
            },
            {  # the realistic regression: put() called from the packer
                "file": "dragonfly2_tpu/trainer/ingest.py",
                "fn": "put",
                "line": 544,
                "target": "device_put",
                "explicit": True,
                "thread": "trainer.fit",
                "count": 7,
            },
            {  # the transfer stage's put: sanctioned
                "file": "dragonfly2_tpu/trainer/ingest.py",
                "fn": "put",
                "line": 544,
                "target": "device_put",
                "explicit": True,
                "thread": "trainer.ingest-transfer",
                "count": 100,
            },
            {  # the named post-stream tail: sanctioned
                "file": "dragonfly2_tpu/trainer/ingest.py",
                "fn": "_ragged_tail",
                "line": 890,
                "target": "device_put",
                "explicit": True,
                "thread": "MainThread",
                "count": 1,
            },
        ],
    }
    report = tmp_path / "jit-witness.json"
    report.write_text(json.dumps(dump))
    res = jaxhygiene.witness_crosscheck(fakepkg, report)
    keys = {f.key for f in res.findings}
    assert keys == {
        "pack-transfer:stream_train_mlp:device_put",
        "pack-transfer:put:device_put",
    }, [f.message for f in res.findings]


def test_jitwitness_crosscheck_ignores_foreign_and_quiet_functions(
    fakepkg, tmp_path
):
    """jax-internal eager ops (not a package jit site) and package
    functions under the signature allowance must NOT fail the join."""
    (fakepkg / "plane.py").write_text(
        "import jax\n\ndef quiet_fn(x):\n    return x\n\nf = jax.jit(quiet_fn)\n"
    )
    dump = {
        "compiles": {
            "convert_element_type": {
                "count": 500,
                "signatures": [f"[s{i}]" for i in range(40)],
            },
            "quiet_fn": {"count": 3, "signatures": ["[a]", "[b]", "[c]"]},
        },
        # a shared memoization helper builds MANY distinct functions'
        # wrappers at one line, one each — per-(site, target) records
        # under the allowance must not read as churn
        "wrapper_sites": [
            {"site": "fakepkg/plane.py:5", "target": f"fwd_{i}", "count": 1}
            for i in range(12)
        ],
        "transfers": [],
    }
    report = tmp_path / "jit-witness.json"
    report.write_text(json.dumps(dump))
    res = jaxhygiene.witness_crosscheck(fakepkg, report)
    assert res.findings == [], [f.message for f in res.findings]


def test_witness_allowlist_entries_never_stale_on_subset_runs(fakepkg, tmp_path):
    """A subset witness run legitimately exercises none of the
    allowlisted storms — witness-pass entries are exempt from the
    stale rule (the full witnessed tier-1 audits them for rot)."""
    (fakepkg / "ok.py").write_text("x = 1\n")
    dump = tmp_path / "jw.json"
    dump.write_text(json.dumps({"compiles": {}, "wrapper_sites": [], "transfers": []}))
    al_file = tmp_path / "allow.txt"
    al_file.write_text(
        "jit-witness retrace:never_seen_here  # audited: full-session-only storm\n"
    )
    al = dfanalyze.Allowlist.load(al_file)
    report = dfanalyze.run(
        package_dir=fakepkg, allowlist=al, jit_witness_report=dump
    )
    assert report["ok"], json.dumps(report["summary"], indent=2)
    assert report["summary"]["stale_allowlist"] == []


def test_jit_witness_report_flag_requires_dump(fakepkg, capsys):
    from hack.dfanalyze.__main__ import main

    (fakepkg / "ok.py").write_text("x = 1\n")
    rc = main(["--jit-witness-report", str(fakepkg / "missing.json"), str(fakepkg)])
    assert rc == 1
    assert "jit-witness report not found" in capsys.readouterr().out


def test_bench_taps_count_compiles_and_h2d():
    """The bench taps (compile_tap/transfer_tap) behind bench.py's
    jit_recompiles_per_fit and h2d_transfers_per_superbatch keys: a
    fresh shape compiles and counts, a warm shape counts zero, and the
    H2D tap sees exactly the numpy→device conversions."""
    pytest.importorskip("jax")
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.trainer import metrics as M

    @jax.jit
    def tap_probe(x):
        return x * 5

    base_compiles = M.JIT_RECOMPILES_TOTAL.value
    with jitwitness.compile_tap() as ct, jitwitness.transfer_tap() as tt:
        tap_probe(jnp.asarray(np.ones((7,), np.float32)))  # compile + 1 h2d
    assert ct.count >= 1
    assert tt.h2d == 1
    assert M.JIT_RECOMPILES_TOTAL.value >= base_compiles + 1  # census-covered series
    with jitwitness.compile_tap() as ct2, jitwitness.transfer_tap() as tt2:
        tap_probe(jnp.asarray(np.ones((7,), np.float32)))  # warm: no compile
    assert ct2.count == 0
    assert tt2.h2d == 1


# ---------------------------------------------------------------------------
# typecheck baseline machinery (runs without mypy installed)
# ---------------------------------------------------------------------------

MYPY_LINE = (
    "dragonfly2_tpu/utils/cache.py:42: error: Incompatible return value"
    ' type (got "None", expected "int")  [return-value]'
)


def test_typecheck_normalize_drops_line_numbers():
    norm = typecheck.normalize(MYPY_LINE)
    assert norm == (
        "dragonfly2_tpu/utils/cache.py|return-value|Incompatible return"
        ' value type (got "None", expected "int")'
    )
    shifted = MYPY_LINE.replace(":42:", ":99:")
    assert typecheck.normalize(shifted) == norm


def test_typecheck_baseline_suppresses_known_and_fails_new(tmp_path):
    base = tmp_path / "baseline.txt"
    typecheck.write_baseline([typecheck.normalize(MYPY_LINE)], base)
    loaded = typecheck.load_baseline(base)
    assert typecheck.findings_against_baseline([MYPY_LINE], loaded) == []
    new_line = MYPY_LINE.replace("cache.py", "digest.py")
    findings = typecheck.findings_against_baseline([new_line], loaded)
    assert len(findings) == 1
    assert "digest.py" in findings[0].message
    assert findings[0].pass_id == "typecheck"


def test_typecheck_skips_cleanly_without_mypy():
    res = typecheck.run(dfanalyze.DEFAULT_PACKAGE)
    if typecheck.mypy_available():  # pragma: no cover - image has no mypy
        assert res.skipped == ""
    else:
        assert "mypy not installed" in res.skipped
        assert res.findings == []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_json(fakepkg, capsys):
    from hack.dfanalyze.__main__ import main

    (fakepkg / "svc.py").write_text(
        """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def sleepy(self):
        with self._lock:
            time.sleep(1.0)
"""
    )
    assert main(["--json", str(fakepkg)]) == 1
    out = capsys.readouterr().out
    report = json.loads(out)
    assert report["ok"] is False
    assert any(
        f["pass"] == "blocking" for p in report["passes"] for f in p["findings"]
    )
    assert main(["--list-passes"]) == 0


def test_check_metrics_shim_still_works():
    """The old entry point forwards to the migrated pass."""
    import importlib
    import sys

    sys.path.insert(0, str(REPO / "hack"))
    try:
        import check_metrics

        importlib.reload(check_metrics)
        assert check_metrics.check() == []
    finally:
        sys.path.remove(str(REPO / "hack"))


PREHEAT_PLANNER_SHAPE_FIXTURE = '''
import threading

class Window:
    """Demand side of the preheat sweep: its lock covers only the
    series dict; snapshots copy out before anything else runs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series = {}

    def observe(self, task_id, count):
        with self._lock:
            self._series[task_id] = self._series.get(task_id, 0.0) + count

    def series_batch(self):
        with self._lock:
            return dict(self._series)


class Planner:
    """Planner side: _lock guards ONLY the recently-planned map and is
    never held across the window, the forecaster, or the resource
    model — each snapshot/forecast happens before the lock, bookkeeping
    after."""

    def __init__(self, window):
        self._lock = threading.Lock()
        self._planned_at = {}
        self.window = window

    def sweep_once(self, now):
        snapshot = self.window.series_batch()  # window lock, then released
        picked = [t for t in snapshot if not self._covered(t, now)]
        with self._lock:
            for task_id in picked:
                self._planned_at[task_id] = now
        return picked

    def _covered(self, task_id, now):
        with self._lock:
            at = self._planned_at.get(task_id)
        return at is not None and now - at < 120.0

    def stats(self):
        with self._lock:
            return {"cooling": len(self._planned_at)}
'''


def test_lockorder_preheat_planner_shape_is_clean(fakepkg):
    """The preheat planner's lock model (Planner._lock for cooldown
    bookkeeping only, Window._lock for the series dict, no hold across
    the other) must analyze clean — the named baseline for the sweep's
    lock shape."""
    (fakepkg / "preheat_planner.py").write_text(PREHEAT_PLANNER_SHAPE_FIXTURE)
    res = lockorder.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_lockorder_catches_a_preheat_nesting_regression(fakepkg):
    """The regression the clean shape guards against: a sweep that
    snapshots the window while holding the planner lock, while the
    window notifies the planner under its own lock — the ABBA a demand
    observer callback could grow."""
    (fakepkg / "preheat_bad.py").write_text(
        '''
import threading

class BadPlanner:
    def __init__(self):
        self._lock = threading.Lock()        # cooldown bookkeeping
        self._demand_lock = threading.Lock() # series dict

    def sweep_once(self):
        with self._lock:
            self._snapshot()  # planner -> demand: held across the window

    def _snapshot(self):
        with self._demand_lock:
            return {}

    def observe(self):
        with self._demand_lock:
            self._note_planned()  # demand -> planner: the inversion

    def _note_planned(self):
        with self._lock:
            pass
'''
    )
    res = lockorder.run(fakepkg)
    cycles = [f for f in res.findings if f.key.startswith("cycle:")]
    assert cycles, [f.message for f in res.findings]
    assert "BadPlanner._lock" in cycles[0].message
    assert "BadPlanner._demand_lock" in cycles[0].message


FLOW_LEDGER_SHAPE_FIXTURE = '''
import threading

_lock = threading.Lock()
_cells = {}
_ring = []


def account(plane, prov, n):
    """The utils/flows.py shape: ONE short module-lock hold per call —
    bump the cell and append the ring tuple, nothing else inside."""
    with _lock:
        _cells[(plane, prov)] = _cells.get((plane, prov), 0) + n
        _ring.append((plane, prov, n))


def snapshot():
    with _lock:
        cells = dict(_cells)
    # derived math (efficiency rollups) happens OUTSIDE the lock
    return {"total": sum(cells.values())}
'''


def test_lockorder_flow_ledger_shape_is_clean(fakepkg):
    """The flow ledger's lock model (one module-level Lock, every
    account()/snapshot() a single non-nesting hold, rollup math outside)
    must analyze clean — the named baseline for the hot-tagged
    utils/flows.py accounting path."""
    (fakepkg / "flows.py").write_text(FLOW_LEDGER_SHAPE_FIXTURE)
    res = lockorder.run(fakepkg)
    assert res.findings == [], [f.message for f in res.findings]


def test_lockorder_catches_a_flow_ledger_reentry_regression(fakepkg):
    """The regression the clean shape guards against: a rollup helper
    that re-acquires the ledger lock from inside account() — a plain
    Lock, so the first piece write would deadlock the daemon."""
    (fakepkg / "flows_bad.py").write_text(
        '''
import threading

_lock = threading.Lock()
_cells = {}


def account(plane, prov, n):
    with _lock:
        _cells[(plane, prov)] = _cells.get((plane, prov), 0) + n
        _efficiency()  # rollup under the hold: re-enters below


def _efficiency():
    with _lock:
        return sum(_cells.values())
'''
    )
    res = lockorder.run(fakepkg)
    assert any(f.key.startswith("self:") for f in res.findings), [
        f.message for f in res.findings
    ]
