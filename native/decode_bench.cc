// Standalone decode-rate driver: feeds a download-record CSV file through
// the DfPairs parser exactly the way schema/native.py does (8 MiB chunks,
// f16 take after every chunk) and prints MB/s + records/s. Used for
// profiling (build with -pg) and for the bench artifact's decode_only_rate.
//
// Usage: decode_bench FILE [passes]

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <vector>

extern "C" {
void* df_pairs_new();
void df_pairs_free(void*);
long df_pairs_feed(void*, const char*, long);
void df_pairs_finish(void*);
long df_pairs_count(void*);
long df_pairs_rows(void*);
long df_pairs_errors(void*);
long df_pairs_take_half(void*, uint16_t*, uint16_t*, int32_t*);
long df_feature_dim();
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s FILE [passes]\n", argv[0]);
    return 2;
  }
  int passes = argc > 2 ? atoi(argv[2]) : 1;
  FILE* f = fopen(argv[1], "rb");
  if (!f) {
    perror("open");
    return 1;
  }
  // Read the whole file up front so the timed loop measures decode, not IO.
  std::vector<char> data;
  {
    char buf[1 << 20];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0)
      data.insert(data.end(), buf, buf + n);
  }
  fclose(f);

  const long F = df_feature_dim();
  const size_t chunk = 8u << 20;
  std::vector<uint16_t> feat, label;
  std::vector<int32_t> idx;
  long rows = 0, pairs = 0;
  auto t0 = std::chrono::steady_clock::now();
  for (int p = 0; p < passes; ++p) {
    void* h = df_pairs_new();
    for (size_t off = 0; off < data.size(); off += chunk) {
      size_t n = data.size() - off < chunk ? data.size() - off : chunk;
      df_pairs_feed(h, data.data() + off, long(n));
      long m = df_pairs_count(h);
      feat.resize(size_t(m) * F);
      label.resize(size_t(m));
      idx.resize(size_t(m));
      pairs += df_pairs_take_half(h, feat.data(), label.data(), idx.data());
    }
    df_pairs_finish(h);
    long m = df_pairs_count(h);
    feat.resize(size_t(m) * F);
    label.resize(size_t(m));
    idx.resize(size_t(m));
    pairs += df_pairs_take_half(h, feat.data(), label.data(), idx.data());
    rows += df_pairs_rows(h);
    df_pairs_free(h);
  }
  auto dt = std::chrono::duration<double>(std::chrono::steady_clock::now() - t0).count();
  double mb = double(data.size()) * passes / 1e6;
  printf("{\"bytes\": %zu, \"passes\": %d, \"records\": %ld, \"pairs\": %ld, "
         "\"seconds\": %.4f, \"mb_per_s\": %.1f, \"records_per_s\": %.1f}\n",
         data.size(), passes, rows, pairs, dt, mb / dt, rows / dt);
  return 0;
}
