// Native ingestion hot path: fused CSV decode + feature extraction.
//
// The reference left its training core a stub, so its ingestion edge is a
// 128MiB-chunk gRPC upload into CSV files (reference
// trainer/storage/storage.go:44-148); the TPU rebuild's north star (1B
// download records in <10min ⇒ ~1.7M rec/s sustained) makes the Python
// csv/numpy decode the bottleneck. This library streams the trainer's
// concatenated-CSV dataset files and emits training tensors directly:
//
//  - DfPairs: download records → (download,parent) pair features [M,18]
//    (kFeatureDim below — kept in lockstep with features.MLP_FEATURE_DIM
//    by the df_feature_dim ABI handshake) + log-cost labels, byte-identical
//    semantics to schema/features.extract_pair_features (the Python
//    fallback).
//  - DfTopo: networktopology records → interned host nodes + probe edge
//    list, matching schema/features.build_probe_graph's interning and
//    last-write-wins edge semantics.
//
// CSV dialect: RFC4180 quotes (python csv.writer). Embedded header lines
// (every upload round re-sends one, trainer service demux) are detected by
// first-column == first header column and re-resolve the column mapping,
// so schema drift between scheduler versions is tolerated per-chunk.
//
// C ABI only — bound from Python via ctypes (schema/native.py).

#include <cmath>
#include <cstddef>  // offsetof — do not rely on <immintrin.h> pulling it in
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <type_traits>
#include <unordered_map>
#include <vector>

#if defined(__AVX2__) || defined(__F16C__)
#include <immintrin.h>
#endif

namespace {

constexpr int kMaxParents = 20;     // schema/records.py MAX_PARENTS
constexpr int kMaxPieces = 10;      // MAX_PIECES_PER_PARENT
constexpr int kMaxDestHosts = 5;    // MAX_DEST_HOSTS
constexpr int kFeatureDim = 19;     // features.MLP_FEATURE_DIM
constexpr int kMaxLocationDepth = 5;
constexpr double kNsPerMs = 1e6;

// ---------------------------------------------------------------------------
// CSV line splitter (RFC4180: quoted fields, "" escapes). Fields are
// returned as string_views into a scratch buffer owned by the caller; the
// unquote path rewrites in place.
// ---------------------------------------------------------------------------

struct FieldRef {
  const char* data;
  size_t len;
  std::string view() const { return std::string(data, len); }
  bool empty() const { return len == 0; }
  bool eq(const char* s) const {
    size_t n = strlen(s);
    return len == n && memcmp(data, s, n) == 0;
  }
};

// Splits one line (excluding trailing \n / \r\n) into fields. `scratch`
// backs unescaped quoted fields. Returns false on malformed quoting.
bool split_csv_line(const char* line, size_t len, std::vector<FieldRef>& out,
                    std::string& scratch) {
  out.clear();
  scratch.clear();
  // Reserve so scratch never reallocates mid-parse (FieldRefs point into it).
  scratch.reserve(len + 1);
  size_t i = 0;
  while (true) {
    if (i < len && line[i] == '"') {
      // quoted field → unescape into scratch
      size_t start = scratch.size();
      ++i;
      while (i < len) {
        if (line[i] == '"') {
          if (i + 1 < len && line[i + 1] == '"') {
            scratch.push_back('"');
            i += 2;
          } else {
            ++i;
            break;
          }
        } else {
          scratch.push_back(line[i++]);
        }
      }
      out.push_back({scratch.data() + start, scratch.size() - start});
      if (i < len) {
        if (line[i] != ',') return false;
        ++i;
        continue;
      }
      break;
    }
    size_t start = i;
    while (i < len && line[i] != ',') ++i;
    out.push_back({line + start, i - start});
    if (i < len) {
      ++i;  // skip comma
      continue;
    }
    break;
  }
  return true;
}

double to_num_slow(const char* p, size_t n) {
  char buf[64];
  size_t m = n < sizeof(buf) - 1 ? n : sizeof(buf) - 1;
  memcpy(buf, p, m);
  buf[m] = '\0';
  return strtod(buf, nullptr);
}

// SWAR digit-run helpers (the classic 8-digits-per-multiply technique —
// same per-digit arithmetic as the scalar loop, so results stay
// bit-identical to the numpy fallback's float()):
//   parse8: 8 ASCII digits → their base-10 value
static inline uint32_t parse8(uint64_t v) {
  v = (v & 0x0F0F0F0F0F0F0F0Full) * 2561 >> 8;
  v = (v & 0x00FF00FF00FF00FFull) * 6553601 >> 16;
  return uint32_t((v & 0x0000FFFF0000FFFFull) * 42949672960001ull >> 32);
}
// Leading digit-byte count of an 8-byte window (little-endian: byte 0 is
// the first character), 0..8.
static inline size_t digit_run_len8(uint64_t v) {
  const uint64_t t =
      ((v & 0xF0F0F0F0F0F0F0F0ull) |
       (((v + 0x0606060606060606ull) & 0xF0F0F0F0F0F0F0F0ull) >> 4)) ^
      0x3333333333333333ull;
  return t ? size_t(__builtin_ctzll(t)) >> 3 : 8;
}

// Extend acc by the digit run starting at p, stopping at the first
// non-digit; returns the run length. 8-byte loads stay within [p, p+len)
// — len is the field remainder, so no read ever crosses the feed
// buffer's end. Per-digit arithmetic is identical to the scalar
// original, so results are bit-equal.
static inline size_t parse_run(const char* p, size_t len, uint64_t& acc) {
  size_t i = 0;
  while (i + 8 <= len) {
    uint64_t v;
    memcpy(&v, p + i, 8);
    const size_t k = digit_run_len8(v);
    if (k == 8) {
      acc = acc * 100000000ull + parse8(v);
      i += 8;
      continue;
    }
    for (size_t j = 0; j < k; ++j)
      acc = acc * 10 + (unsigned(p[i + j]) - '0');
    return i + k;
  }
  for (; i < len; ++i) {
    const unsigned d = unsigned(p[i]) - '0';
    if (d > 9) break;
    acc = acc * 10 + d;
  }
  return i;
}

// Fast decimal parse for the hot path: [-]digits[.digits]; anything else
// (exponents, >18 digits on either side of the dot, inf/nan) falls back
// to strtod. CSV numbers here are host stats (long float reprs) and ns
// costs (10-13 digit ints), so the fast path covers ~all fields — with
// no libc calls. The accumulation order (integer build-up, then one
// double add+divide) matches the scalar original exactly — parity with
// the Python fallback. (Divergence note: >18 fractional digits now go
// to strtod — correctly rounded, like Python's float() — where the old
// loop truncated; double reprs carry ≤17 digits, so self-produced files
// never hit this.)
double parse_num(const char* p, size_t n) {
  if (n == 0) return 0.0;
  static const double kPow10[] = {1.0,    1e1,  1e2,  1e3,  1e4,  1e5,  1e6,
                                  1e7,    1e8,  1e9,  1e10, 1e11, 1e12, 1e13,
                                  1e14,   1e15, 1e16, 1e17, 1e18};
  const size_t s = (p[0] == '-') ? 1 : 0;
  const bool neg = s != 0;
  uint64_t ip = 0;
  const size_t li = parse_run(p + s, n - s, ip);  // integer-part digits
  // li > 18: ip may have wrapped, but it is never used — strtod takes over
  if (li == 0 || li > 18) return to_num_slow(p, n);
  const size_t dot = s + li;
  if (dot == n) return neg ? -double(ip) : double(ip);
  if (p[dot] != '.') return to_num_slow(p, n);
  uint64_t fp = 0;
  const size_t lf = parse_run(p + dot + 1, n - dot - 1, fp);
  if (dot + 1 + lf != n || lf > 18) return to_num_slow(p, n);
  const double v = double(ip) + double(fp) / kPow10[lf];
  return neg ? -v : v;
}

double to_num(const FieldRef& f) { return parse_num(f.data, f.len); }

// Shared leading "|"-separated path depth / kMaxLocationDepth
// (features.location_affinity). Operates on line views — no allocation.
double location_affinity(const char* pa, size_t na, const char* pb, size_t nb) {
  if (na == 0 || nb == 0) return 0.0;
  int depth = 0;
  size_t ia = 0, ib = 0;
  for (int d = 0; d < kMaxLocationDepth; ++d) {
    if (ia > na || ib > nb) break;
    const char* ca =
        static_cast<const char*>(memchr(pa + ia, '|', na - ia));
    const char* cb =
        static_cast<const char*>(memchr(pb + ib, '|', nb - ib));
    size_t la = (ca ? size_t(ca - pa) : na) - ia;
    size_t lb = (cb ? size_t(cb - pb) : nb) - ib;
    if (la != lb || memcmp(pa + ia, pb + ib, la) != 0) break;
    ++depth;
    if (!ca || !cb) break;
    ia = size_t(ca - pa) + 1;
    ib = size_t(cb - pb) + 1;
  }
  return double(depth) / kMaxLocationDepth;
}

// ---------------------------------------------------------------------------
// Streaming record feeder: buffers partial records across feed() chunks.
// A newline inside an RFC4180 quoted field is data, not a record break, so
// quote parity is tracked across chunks (csv.writer quotes any field
// containing the quote char, so parity toggling on every '"' is exact for
// writer-produced files).
// ---------------------------------------------------------------------------

// Bounded carry: a legitimate record is tens of KB; a multi-megabyte
// carry means corrupt input (an unterminated quote swallowing the rest
// of the stream). Discard it, reset quote parity, resync at the next
// newline — corruption costs a bounded window, not the whole file.
constexpr size_t kMaxCarry = 8 * 1024 * 1024;

template <typename RowFn, typename DiscardFn>
void feed_lines(std::string& carry, bool& in_quotes, const char* buf, long len,
                RowFn&& on_line, DiscardFn&& on_discard) {
  long pos = 0;
  // Lazy quote tracking: quotes are rare (csv.writer only quotes fields
  // containing separators/quotes), so instead of scanning every line for
  // '"' we keep a cursor to the NEXT quote at-or-after `pos`. Lines that
  // end before it need no parity work and no per-line quote memchr —
  // the common case is then two byte passes total ('\n' here, ',' in the
  // row scanner) instead of four.
  long next_quote = -1;  // -1: unknown; len: none remaining
  auto quote_at_or_after = [&](long p) -> long {
    if (next_quote < p) {
      const char* qp =
          static_cast<const char*>(memchr(buf + p, '"', size_t(len - p)));
      next_quote = qp ? long(qp - buf) : len;
    }
    return next_quote;
  };
  while (pos < len) {
    const char* nl =
        static_cast<const char*>(memchr(buf + pos, '\n', size_t(len - pos)));
    long end = nl ? long(nl - buf) : len;
    // quote parity over [pos, end): all segment quotes precede the
    // newline, so parity-after tells whether the newline is data
    long q = quote_at_or_after(pos);
    bool has_quote = q < end;
    while (q < end) {
      in_quotes = !in_quotes;
      const char* qp = static_cast<const char*>(
          memchr(buf + q + 1, '"', size_t(len - q - 1)));
      next_quote = qp ? long(qp - buf) : len;
      q = next_quote;
    }
    if (!nl) {  // chunk ends mid-record
      carry.append(buf + pos, size_t(len - pos));
      if (carry.size() > kMaxCarry) {
        carry.clear();
        in_quotes = false;
        on_discard();
      }
      return;
    }
    if (in_quotes) {  // newline inside a quoted field is data
      carry.append(buf + pos, size_t(end - pos + 1));
      if (carry.size() > kMaxCarry) {
        carry.clear();
        in_quotes = false;
        on_discard();
      }
      pos = end + 1;
      continue;
    }
    if (!carry.empty()) {
      carry.append(buf + pos, size_t(end - pos));
      size_t L = carry.size();
      if (L && carry[L - 1] == '\r') --L;
      on_line(carry.data(), L, true);  // conservative: carry may hold quotes
      carry.clear();
    } else {
      size_t L = size_t(end - pos);
      if (L && buf[end - 1] == '\r') --L;
      on_line(buf + pos, L, has_quote);
    }
    pos = end + 1;
  }
}

// ---------------------------------------------------------------------------
// Download-record pair decoder
// ---------------------------------------------------------------------------

// Dispatch ops: one tiny op per hot column, with the destination encoded
// as a byte offset into the per-parent (or child) scratch struct resolved
// at header time. OP_NUM covers ~90% of hot fields, so the dispatch
// branch is effectively free; the old 27-way kind switch cost ~45
// cycles/field in calls + branch misses.
enum Op : uint8_t {
  OP_IGNORE = 0,
  OP_NUM,           // parse_num → double at offset
  OP_FLAG_TRUE,     // non-empty field → bool true at offset (parent id)
  OP_EQ_SUCCEEDED,  // bool at offset = (field == "Succeeded")
  OP_NE_NORMAL,     // bool at offset = (field != "normal")
  OP_STR,           // StrRef at offset → view into the current line
};

// 0xff in `parent` selects the child/task scratch as the offset base.
constexpr uint8_t kChildBase = 0xff;

struct ColAction {
  uint8_t op = OP_IGNORE;
  uint8_t parent = kChildBase;
  uint16_t offset = 0;
};

// View into the line being scanned (or the unquote scratch). Valid only
// until the next line — emit_row consumes it within the same on_line
// call, so no copy is ever needed (the old std::string assigns were two
// allocations per populated parent per row). No default initializers:
// keeps the scratch structs trivial so reset() is one memset (every
// member is zeroed there or fully written before any read).
struct StrRef {
  const char* data;
  uint32_t len;
  bool empty() const { return len == 0; }
};

// POD scratch: reset is one memset. Field order groups the doubles first
// so offsetof stays simple; StrRef/null resets to empty via zeroing.
struct ParentScratch {
  double fin, upload_count, upload_failed, cul, cuc;
  double cpu, mem, tcp, utcp, disk;
  double cpu_proc, mem_avail, mem_total, inodes;
  double piece_cost[kMaxPieces];
  StrRef idc, loc;
  bool has_id, succeeded, is_seed;
  void reset() { memset(this, 0, sizeof(*this)); }
};
static_assert(std::is_trivially_copyable<ParentScratch>::value,
              "memset reset requires a trivially-copyable scratch");

struct ChildScratch {
  double total_pieces, cpu, mem, task_len;
  StrRef idc, loc;
  void reset() { memset(this, 0, sizeof(*this)); }
};
static_assert(std::is_trivially_copyable<ChildScratch>::value,
              "memset reset requires a trivially-copyable scratch");

struct DfPairs {
  std::vector<ColAction> colmap;
  std::vector<uint32_t> hot_cols;  // ascending indices of non-ignored columns
  std::vector<uint32_t> skip_on_empty;  // hot-index jump when a parent id is empty
  std::string header_col0;
  std::string carry;        // partial record across feed() chunks
  bool in_quotes = false;   // RFC4180 quote parity across chunks
  std::string scratch;      // unquote buffer
  std::vector<FieldRef> fields;
  ParentScratch parents[kMaxParents];
  ChildScratch child;
  int64_t row = 0;  // download-record counter (not counting headers)
  int64_t errors = 0;

  std::vector<float> feat;    // M * kFeatureDim
  std::vector<float> label;   // M
  std::vector<int32_t> index; // M — source download row

  void resolve_header(const std::vector<FieldRef>& hs) {
    colmap.assign(hs.size(), ColAction{});
    header_col0 = hs.empty() ? "" : hs[0].view();
    for (size_t c = 0; c < hs.size(); ++c) {
      std::string name = hs[c].view();
      ColAction a;
      auto child_num = [&](size_t off) {
        a.op = OP_NUM;
        a.parent = kChildBase;
        a.offset = uint16_t(off);
      };
      if (name == "task.total_piece_count") {
        child_num(offsetof(ChildScratch, total_pieces));
      } else if (name == "task.content_length") {
        child_num(offsetof(ChildScratch, task_len));
      } else if (name == "host.cpu.percent") {
        child_num(offsetof(ChildScratch, cpu));
      } else if (name == "host.memory.used_percent") {
        child_num(offsetof(ChildScratch, mem));
      } else if (name == "host.network.idc") {
        a = {OP_STR, kChildBase, uint16_t(offsetof(ChildScratch, idc))};
      } else if (name == "host.network.location") {
        a = {OP_STR, kChildBase, uint16_t(offsetof(ChildScratch, loc))};
      } else if (name.rfind("parents.", 0) == 0) {
        const char* p = name.c_str() + 8;
        char* end;
        long slot = strtol(p, &end, 10);
        if (end == p || *end != '.' || slot < 0 || slot >= kMaxParents) {
          colmap[c] = a;
          continue;
        }
        std::string rest(end + 1);
        const uint8_t pa = uint8_t(slot);
        auto num = [&](size_t off) {
          a = {OP_NUM, pa, uint16_t(off)};
        };
        if (rest == "id") a = {OP_FLAG_TRUE, pa, uint16_t(offsetof(ParentScratch, has_id))};
        else if (rest == "state") a = {OP_EQ_SUCCEEDED, pa, uint16_t(offsetof(ParentScratch, succeeded))};
        else if (rest == "finished_piece_count") num(offsetof(ParentScratch, fin));
        else if (rest == "host.upload_count") num(offsetof(ParentScratch, upload_count));
        else if (rest == "host.upload_failed_count") num(offsetof(ParentScratch, upload_failed));
        else if (rest == "host.concurrent_upload_limit") num(offsetof(ParentScratch, cul));
        else if (rest == "host.concurrent_upload_count") num(offsetof(ParentScratch, cuc));
        else if (rest == "host.type") a = {OP_NE_NORMAL, pa, uint16_t(offsetof(ParentScratch, is_seed))};
        else if (rest == "host.network.idc") a = {OP_STR, pa, uint16_t(offsetof(ParentScratch, idc))};
        else if (rest == "host.network.location") a = {OP_STR, pa, uint16_t(offsetof(ParentScratch, loc))};
        else if (rest == "host.cpu.percent") num(offsetof(ParentScratch, cpu));
        else if (rest == "host.memory.used_percent") num(offsetof(ParentScratch, mem));
        else if (rest == "host.network.tcp_connection_count") num(offsetof(ParentScratch, tcp));
        else if (rest == "host.network.upload_tcp_connection_count") num(offsetof(ParentScratch, utcp));
        else if (rest == "host.disk.used_percent") num(offsetof(ParentScratch, disk));
        else if (rest == "host.cpu.process_percent") num(offsetof(ParentScratch, cpu_proc));
        else if (rest == "host.memory.available") num(offsetof(ParentScratch, mem_avail));
        else if (rest == "host.memory.total") num(offsetof(ParentScratch, mem_total));
        else if (rest == "host.disk.inodes_used_percent") num(offsetof(ParentScratch, inodes));
        else if (rest.rfind("pieces.", 0) == 0) {
          const char* q = rest.c_str() + 7;
          long pj = strtol(q, &end, 10);
          if (end != q && strcmp(end, ".cost") == 0 && pj >= 0 && pj < kMaxPieces) {
            num(offsetof(ParentScratch, piece_cost) + sizeof(double) * size_t(pj));
          }
        }
      }
      colmap[c] = a;
    }
    hot_cols.clear();
    for (size_t c = 0; c < colmap.size(); ++c)
      if (colmap[c].op != OP_IGNORE) hot_cols.push_back(uint32_t(c));
    // Empty-slot fast-forward: when a parent's id column is empty the
    // whole slot is padding, so the scan can jump to the first hot column
    // NOT belonging to that parent. This is what keeps 20-slot padded
    // rows near the cost of their populated prefix. The id column is the
    // only OP_FLAG_TRUE op, so it identifies slot starts.
    skip_on_empty.assign(hot_cols.size(), 0);
    for (size_t hi = 0; hi < hot_cols.size(); ++hi) {
      const ColAction a = colmap[hot_cols[hi]];
      if (a.op != OP_FLAG_TRUE) continue;
      size_t hj = hi + 1;
      while (hj < hot_cols.size()) {
        const ColAction b = colmap[hot_cols[hj]];
        if (b.parent != a.parent) break;  // kChildBase never matches a slot
        ++hj;
      }
      skip_on_empty[hi] = uint32_t(hj);
    }
  }

  inline void dispatch(const ColAction a, const char* p, size_t n) {
    // empty fields (padding parent slots) keep their reset() defaults —
    // skipping them is what makes padded 20-slot rows cheap
    if (n == 0) return;
    char* base = a.parent == kChildBase
                     ? reinterpret_cast<char*>(&child)
                     : reinterpret_cast<char*>(&parents[a.parent]);
    switch (a.op) {
      case OP_NUM:
        *reinterpret_cast<double*>(base + a.offset) = parse_num(p, n);
        return;
      case OP_FLAG_TRUE:
        *reinterpret_cast<bool*>(base + a.offset) = true;
        return;
      case OP_EQ_SUCCEEDED:
        *reinterpret_cast<bool*>(base + a.offset) =
            (n == 9 && memcmp(p, "Succeeded", 9) == 0);
        return;
      case OP_NE_NORMAL:
        *reinterpret_cast<bool*>(base + a.offset) =
            !(n == 6 && memcmp(p, "normal", 6) == 0);
        return;
      case OP_STR:
        *reinterpret_cast<StrRef*>(base + a.offset) = {p, uint32_t(n)};
        return;
      default:
        return;
    }
  }

  void reset_scratch() {
    child.reset();
    for (auto& p : parents) p.reset();
  }

  bool looks_like_header(const char* line, size_t len) const {
    const size_t h = header_col0.size();
    return h && len >= h && memcmp(line, header_col0.data(), h) == 0 &&
           (len == h || line[h] == ',');
  }

  void on_line(const char* line, size_t len, bool has_quote = true) {
    if (len == 0) return;
    if (colmap.empty() || has_quote || looks_like_header(line, len)) {
      on_line_slow(line, len);
      return;
    }
    reset_scratch();
    scan_row_fast(line, len);
    emit_row();
    ++row;
  }

  // Header lines and RFC4180-quoted rows: full split + mapped walk.
  void on_line_slow(const char* line, size_t len) {
    if (!split_csv_line(line, len, fields, scratch)) {
      ++errors;
      return;
    }
    // Header detection: no mapping yet, or first column repeats the
    // header's first column name (embedded header of a later upload).
    if (colmap.empty() || (!fields.empty() && !header_col0.empty() &&
                           fields[0].eq(header_col0.c_str()))) {
      resolve_header(fields);
      return;
    }
    reset_scratch();
    size_t n = fields.size() < colmap.size() ? fields.size() : colmap.size();
    for (size_t c = 0; c < n; ++c) {
      const ColAction a = colmap[c];
      if (a.op == OP_IGNORE) continue;
      dispatch(a, fields[c].data, fields[c].len);
    }
    emit_row();
    ++row;
  }

  // Tail short-circuit: called when a parent id column is empty. If every
  // byte from `from` up to the line's second-to-last comma is a comma,
  // then all remaining parent columns are empty (only the trailing
  // created_at/updated_at — never hot — carry data), so the scan can stop
  // for the whole row. Exact for any input: a later parent that DID have
  // data would put a non-comma byte inside the checked span (its id and
  // any piece-cost column are never the final two fields — the schema
  // keeps them ≥2 columns apart), failing the check and falling back to
  // the normal scan.
  //
  // Scope note: since columnar.write_csv's skip_padding change (round 5)
  // OUR writer serializes padding slots as EMPTY cells, so this fires on
  // every self-produced row with spare parent capacity — skipping the
  // padding tail wholesale is part of the measured decode win. On
  // "0"-padded files (older rounds, gocsv-style writers) the check fails
  // at the first "0" and costs one bounded extra scan per row
  // (`tried_tail`).
  static bool tail_is_padding(const char* line, size_t len, size_t from) {
    long p_last = -1, p_prev = -1;
    for (long j = long(len) - 1; j >= long(from); --j) {
      if (line[j] == ',') {
        if (p_last < 0) {
          p_last = j;
        } else {
          p_prev = j;
          break;
        }
      }
    }
    if (p_prev < 0) return false;
    size_t i = from;
#if defined(__AVX2__)
    const __m256i commas = _mm256_set1_epi8(',');
    for (; i + 32 <= size_t(p_prev); i += 32) {
      const __m256i v =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(line + i));
      if (uint32_t(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, commas))) !=
          0xffffffffu)
        return false;
    }
#endif
    for (; i < size_t(p_prev); ++i)
      if (line[i] != ',') return false;
    return true;
  }

  // Unquoted data rows (the overwhelmingly common case): one pass over the
  // line, finding commas 32 bytes at a time (AVX2) and materializing only
  // the ~hot columns the feature extractor reads. Runs of ignored columns
  // — including the empty padding parent slots — are consumed by popcount
  // without touching individual fields.
  void scan_row_fast(const char* line, size_t len) {
    const size_t nhot = hot_cols.size();
    size_t hi = 0;
    uint32_t next_hot = nhot ? hot_cols[0] : 0xffffffffu;
    uint32_t c = 0;        // current column index
    size_t field_start = 0;
    size_t i = 0;
    bool tried_tail = false;  // attempt the tail short-circuit once per row
#if defined(__AVX2__)
    const __m256i commas = _mm256_set1_epi8(',');
    while (i + 32 <= len && hi < nhot) {
      const __m256i v =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(line + i));
      uint32_t m =
          uint32_t(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, commas)));
      if (m == 0) {
        i += 32;
        continue;
      }
      uint32_t cnt = uint32_t(__builtin_popcount(m));
      if (c + cnt < next_hot) {
        // every comma in this block belongs to ignored columns — consume
        // them in bulk; the in-progress field after the block starts
        // right past the last comma
        c += cnt;
        field_start = i + size_t(31 - __builtin_clz(m)) + 1;
        i += 32;
        continue;
      }
#if defined(__BMI2__)
      // The block holds ≥1 hot-column boundary. Jump straight to each hot
      // field's bounding commas with pdep (deposit selects the k-th set
      // bit) instead of iterating every comma — populated rows have ~7×
      // more commas than hot columns.
      while (true) {
        // next_hot's field ends at overall comma #next_hot, which is the
        // (next_hot - c)-th comma (0-based) of the remaining mask
        uint32_t k = next_hot - c;
        if (k >= cnt) {  // ends beyond this block: consume the rest
          c += cnt;
          field_start = i + size_t(31 - __builtin_clz(m)) + 1;
          break;
        }
        if (k > 0) {  // field starts after the (k-1)-th remaining comma
          const uint32_t before = uint32_t(_pdep_u32(1u << (k - 1), m));
          field_start = i + size_t(__builtin_ctz(before)) + 1;
        }
        const uint32_t at = uint32_t(_pdep_u32(1u << k, m));
        const size_t pos = i + size_t(__builtin_ctz(at));
        const size_t flen = pos - field_start;
        if (flen == 0 && skip_on_empty[hi]) {
          if (!tried_tail) {
            tried_tail = true;
            if (tail_is_padding(line, len, pos + 1)) return;
          }
          hi = skip_on_empty[hi];  // empty parent id → skip the slot
        } else {
          dispatch(colmap[c + k], line + field_start, flen);
          ++hi;
        }
        next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
        // consume commas up to and including the field-ending one
        const uint32_t used = k + 1;
        c += used;
        cnt -= used;
        field_start = pos + 1;
        if (hi >= nhot) return;
        if (cnt == 0) break;  // before the shift: `<< 32` would be UB
        m = uint32_t(_pdep_u32(0xffffffffu << used, m)) & m;
      }
#else
      while (m) {
        const uint32_t b = uint32_t(__builtin_ctz(m));
        m &= m - 1;
        const size_t pos = i + b;
        if (c == next_hot) {
          const size_t flen = pos - field_start;
          if (flen == 0 && skip_on_empty[hi]) {
            if (!tried_tail) {
              tried_tail = true;
              if (tail_is_padding(line, len, pos + 1)) return;
            }
            hi = skip_on_empty[hi];  // empty parent id → skip the slot
          } else {
            dispatch(colmap[c], line + field_start, flen);
            ++hi;
          }
          next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
        }
        ++c;
        field_start = pos + 1;
        if (hi >= nhot) return;
      }
#endif
      i += 32;
    }
#endif
    for (; i < len && hi < nhot; ++i) {
      if (line[i] != ',') continue;
      if (c == next_hot) {
        const size_t flen = i - field_start;
        if (flen == 0 && skip_on_empty[hi]) {
          if (!tried_tail) {
            tried_tail = true;
            if (tail_is_padding(line, len, i + 1)) return;
          }
          hi = skip_on_empty[hi];
        } else {
          dispatch(colmap[c], line + field_start, flen);
          ++hi;
        }
        next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
      }
      ++c;
      field_start = i + 1;
    }
    // trailing field (no comma after the last column)
    if (hi < nhot && c == next_hot && field_start <= len)
      dispatch(colmap[c], line + field_start, len - field_start);
  }

  void emit_row() {
    double total = child.total_pieces > 1.0 ? child.total_pieces : 1.0;
    // per-row invariants: identical values to computing them per pair
    // (pure hoisting — parity with the numpy path is preserved), but one
    // log1p per row instead of one per parent
    const double child_cpu_t = child.cpu / 100.0;
    const double child_mem_t = child.mem / 100.0;
    const double task_len_t =
        log1p(child.task_len > 0 ? child.task_len : 0.0) / 30.0;
    for (int s = 0; s < kMaxParents; ++s) {
      ParentScratch& p = parents[s];
      if (!p.has_id) continue;
      double cost_sum = 0;
      int cost_cnt = 0;
      for (double c : p.piece_cost)
        if (c > 0) {
          cost_sum += c;
          ++cost_cnt;
        }
      if (cost_cnt == 0) continue;  // mask: valid_parent & (cost_cnt > 0)

      double finished_ratio = p.fin / total;
      if (finished_ratio < 0) finished_ratio = 0;
      if (finished_ratio > 1) finished_ratio = 1;
      double upc = p.upload_count > 1.0 ? p.upload_count : 1.0;
      double upload_success = (p.upload_count - p.upload_failed) / upc;
      double cul = p.cul > 1.0 ? p.cul : 1.0;
      double free_upload = 1.0 - p.cuc / cul;
      if (free_upload < 0) free_upload = 0;
      if (free_upload > 1) free_upload = 1;
      bool idc_match = !p.idc.empty() && p.idc.len == child.idc.len &&
                       memcmp(p.idc.data, child.idc.data, p.idc.len) == 0;

      double mem_total = p.mem_total > 1.0 ? p.mem_total : 1.0;
      const double f[kFeatureDim] = {
          finished_ratio,
          upload_success,
          free_upload,
          p.is_seed ? 1.0 : 0.0,
          idc_match ? 1.0 : 0.0,
          location_affinity(child.loc.data, child.loc.len, p.loc.data,
                            p.loc.len),
          p.cpu / 100.0,
          p.mem / 100.0,
          log1p(p.tcp) / 10.0,
          log1p(p.utcp) / 10.0,
          p.disk / 100.0,
          p.succeeded ? 1.0 : 0.0,
          p.cpu_proc / 100.0,
          p.mem_avail / mem_total,
          p.inodes / 100.0,
          child_cpu_t,
          child_mem_t,
          task_len_t,
          0.0,  // rtt_affinity: live-topology feature, 0.0 offline
      };
      // one grow per pair, then straight-line stores (push_back's
      // per-element capacity branch defeats vectorization here)
      const size_t base = feat.size();
      feat.resize(base + kFeatureDim);
      float* dst = feat.data() + base;
      for (int k = 0; k < kFeatureDim; ++k) dst[k] = float(f[k]);
      double mean_cost_ms = cost_sum / cost_cnt / kNsPerMs;
      label.push_back(float(log1p(mean_cost_ms)));
      index.push_back(int32_t(row));
    }
  }

  // End-of-file boundary: flush a trailing record that has no newline and
  // reset quote parity, so concatenating the next file (or pass) cannot
  // bleed this file's tail into its first record. Safe to call once per
  // file mid-stream — parser column mapping survives.
  void finish() {
    if (!carry.empty()) {
      std::string tail;
      tail.swap(carry);
      size_t L = tail.size();
      if (L && tail[L - 1] == '\r') --L;
      on_line(tail.data(), L);
    }
    in_quotes = false;
  }
};

// ---------------------------------------------------------------------------
// Network-topology graph decoder
// ---------------------------------------------------------------------------

enum TopoCol : uint8_t {
  T_IGNORE = 0,
  T_SRC_ID,
  T_SRC_TYPE,
  T_SRC_TCP,
  T_SRC_UTCP,
  D_ID,
  D_TYPE,
  D_TCP,
  D_UTCP,
  D_RTT,
};

struct TopoColAction {
  uint8_t kind = T_IGNORE;
  uint8_t dest = 0;
};

struct DestScratch {
  std::string id;
  bool is_seed = false;
  double tcp = 0, utcp = 0, rtt = 0;
  void reset() {
    id.clear();
    is_seed = false;
    tcp = utcp = rtt = 0;
  }
};

struct DfTopo {
  std::vector<TopoColAction> colmap;
  std::string header_col0;
  std::string carry, scratch;
  bool in_quotes = false;   // RFC4180 quote parity across chunks
  std::vector<FieldRef> fields;
  int64_t errors = 0;
  int64_t row = 0;          // topology-record counter (not counting headers)

  // interned nodes (first-appearance order, like the Python dict)
  std::unordered_map<std::string, int32_t> index;
  std::vector<std::string> node_ids;
  std::vector<float> is_seed, tcp, utcp;

  // edges, insertion-ordered with last-write-wins RTT
  std::unordered_map<uint64_t, size_t> edge_index;
  std::vector<int32_t> src, dst;
  std::vector<double> rtt_ns;

  std::string src_id, src_type;
  double src_tcp = 0, src_utcp = 0;
  DestScratch dests[kMaxDestHosts];

  int32_t intern(const std::string& hid, bool seed, double t, double u) {
    auto it = index.find(hid);
    if (it == index.end()) {
      int32_t idx = int32_t(node_ids.size());
      index.emplace(hid, idx);
      node_ids.push_back(hid);
      is_seed.push_back(seed ? 1.0f : 0.0f);
      tcp.push_back(float(t));
      utcp.push_back(float(u));
      return idx;
    }
    // refresh load stats, last write wins (features.build_probe_graph)
    tcp[it->second] = float(t);
    utcp[it->second] = float(u);
    return it->second;
  }

  void resolve_header(const std::vector<FieldRef>& hs) {
    colmap.assign(hs.size(), TopoColAction{});
    header_col0 = hs.empty() ? "" : hs[0].view();
    for (size_t c = 0; c < hs.size(); ++c) {
      std::string name = hs[c].view();
      TopoColAction a;
      if (name == "host.id") a.kind = T_SRC_ID;
      else if (name == "host.type") a.kind = T_SRC_TYPE;
      else if (name == "host.network.tcp_connection_count") a.kind = T_SRC_TCP;
      else if (name == "host.network.upload_tcp_connection_count") a.kind = T_SRC_UTCP;
      else if (name.rfind("dest_hosts.", 0) == 0) {
        const char* p = name.c_str() + 11;
        char* end;
        long slot = strtol(p, &end, 10);
        if (end == p || *end != '.' || slot < 0 || slot >= kMaxDestHosts) {
          colmap[c] = a;
          continue;
        }
        std::string rest(end + 1);
        a.dest = uint8_t(slot);
        if (rest == "id") a.kind = D_ID;
        else if (rest == "type") a.kind = D_TYPE;
        else if (rest == "network.tcp_connection_count") a.kind = D_TCP;
        else if (rest == "network.upload_tcp_connection_count") a.kind = D_UTCP;
        else if (rest == "probes.average_rtt") a.kind = D_RTT;
      }
      colmap[c] = a;
    }
  }

  void on_line(const char* line, size_t len, bool = true) {
    if (len == 0) return;
    if (!split_csv_line(line, len, fields, scratch)) {
      ++errors;
      return;
    }
    if (colmap.empty() || (!fields.empty() && !header_col0.empty() &&
                           fields[0].eq(header_col0.c_str()))) {
      resolve_header(fields);
      return;
    }
    src_id.clear();
    src_type.clear();
    src_tcp = src_utcp = 0;
    for (auto& d : dests) d.reset();

    size_t n = fields.size() < colmap.size() ? fields.size() : colmap.size();
    for (size_t c = 0; c < n; ++c) {
      const TopoColAction a = colmap[c];
      if (a.kind == T_IGNORE) continue;
      const FieldRef& f = fields[c];
      DestScratch& d = dests[a.dest];
      switch (a.kind) {
        case T_SRC_ID: src_id = f.view(); break;
        case T_SRC_TYPE: src_type = f.view(); break;
        case T_SRC_TCP: src_tcp = to_num(f); break;
        case T_SRC_UTCP: src_utcp = to_num(f); break;
        case D_ID: d.id = f.view(); break;
        case D_TYPE: d.is_seed = !f.empty() && !f.eq("normal"); break;
        case D_TCP: d.tcp = to_num(f); break;
        case D_UTCP: d.utcp = to_num(f); break;
        case D_RTT: d.rtt = to_num(f); break;
        default: break;
      }
    }
    ++row;
    // the Python spec (features.build_probe_graph) interns the src
    // UNCONDITIONALLY — even an empty id becomes a node — and skips
    // only empty dests; matching exactly keeps node indices aligned
    // between the native and numpy paths (the parity contract)
    bool src_seed = !src_type.empty() && src_type != "normal";
    int32_t s = intern(src_id, src_seed, src_tcp, src_utcp);
    for (auto& d : dests) {
      if (d.id.empty()) continue;
      int32_t t = intern(d.id, d.is_seed, d.tcp, d.utcp);
      if (d.rtt > 0) {
        uint64_t key = (uint64_t(uint32_t(s)) << 32) | uint32_t(t);
        auto it = edge_index.find(key);
        if (it == edge_index.end()) {
          edge_index.emplace(key, src.size());
          src.push_back(s);
          dst.push_back(t);
          rtt_ns.push_back(d.rtt);
        } else {
          rtt_ns[it->second] = d.rtt;
        }
      }
    }
  }

  void finish() {
    if (!carry.empty()) {
      std::string tail;
      tail.swap(carry);
      size_t L = tail.size();
      if (L && tail[L - 1] == '\r') --L;
      on_line(tail.data(), L);
    }
    in_quotes = false;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

DfPairs* df_pairs_new() { return new DfPairs(); }
void df_pairs_free(DfPairs* d) { delete d; }

long df_pairs_feed(DfPairs* d, const char* buf, long len) {
  feed_lines(
      d->carry, d->in_quotes, buf, len,
      [d](const char* line, size_t L, bool hq) { d->on_line(line, L, hq); },
      [d]() { ++d->errors; });
  return long(d->label.size());
}

void df_pairs_finish(DfPairs* d) { d->finish(); }
long df_pairs_count(DfPairs* d) { return long(d->label.size()); }
long df_pairs_rows(DfPairs* d) { return long(d->row); }
long df_pairs_errors(DfPairs* d) { return long(d->errors); }

void df_pairs_export(DfPairs* d, float* feat, float* label, int32_t* idx) {
  memcpy(feat, d->feat.data(), d->feat.size() * sizeof(float));
  memcpy(label, d->label.data(), d->label.size() * sizeof(float));
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
}

// Streaming variant: export the pairs accumulated since the last take and
// clear the buffers, so a long decode runs in bounded memory (caller
// sizes the output with df_pairs_count between feed and take — same
// thread drives both). Parser state (carry, colmap) is untouched, so
// takes interleave freely with feeds mid-stream.
long df_pairs_take(DfPairs* d, float* feat, float* label, int32_t* idx) {
  long m = long(d->label.size());
  memcpy(feat, d->feat.data(), d->feat.size() * sizeof(float));
  memcpy(label, d->label.data(), d->label.size() * sizeof(float));
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
  d->feat.clear();
  d->label.clear();
  d->index.clear();
  return m;
}

// f32 → IEEE half (round-to-nearest-even) for the reduced-precision
// device feed: converting at take time keeps the vectors cache-hot and
// moves the cast off the GIL-held Python packing loop (the consumer is
// the bottleneck on small hosts). F16C does 8 lanes per instruction when
// the build arch has it; the scalar path is the bit-exact fallback.
static inline uint16_t f32_to_f16(float v) {
  uint32_t x;
  memcpy(&x, &v, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  int32_t exp = int32_t((x >> 23) & 0xff) - 127 + 15;
  uint32_t mant = x & 0x7fffffu;
  if (exp >= 31) {
    // inf/overflow → ±inf; NaN keeps a mantissa bit (strtod parses the
    // literal "nan" in CSV stats, and the F16C path / np.float16 both
    // preserve it — silently turning NaN into inf would make the
    // half-precision feed differ by build architecture)
    bool is_nan = (int32_t((x >> 23) & 0xff) == 0xff) && mant != 0;
    return uint16_t(sign | 0x7c00u | (is_nan ? 0x0200u : 0u));
  }
  if (exp <= 0) {
    if (exp < -10) return uint16_t(sign);
    mant |= 0x800000u;
    uint32_t shift = uint32_t(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1))) ++half;
    return uint16_t(sign | half);
  }
  uint32_t half = uint32_t(exp << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;
  return uint16_t(sign | half);
}

static void f32_to_f16_buf(const float* in, uint16_t* out, size_t n) {
#if defined(__F16C__)
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 v = _mm256_loadu_ps(in + i);
    __m128i h = _mm256_cvtps_ph(v, _MM_FROUND_TO_NEAREST_INT);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + i), h);
  }
  for (; i < n; ++i) out[i] = f32_to_f16(in[i]);
#else
  for (size_t i = 0; i < n; ++i) out[i] = f32_to_f16(in[i]);
#endif
}

// ABI handshake: the binding layer refuses a library whose feature
// width disagrees with the python schema (a stale prebuilt .so via
// DF_NATIVE_LIB would otherwise fill misaligned tensors silently).
long df_feature_dim() { return kFeatureDim; }

long df_pairs_take_half(DfPairs* d, uint16_t* feat, uint16_t* label, int32_t* idx) {
  long m = long(d->label.size());
  f32_to_f16_buf(d->feat.data(), feat, d->feat.size());
  f32_to_f16_buf(d->label.data(), label, d->label.size());
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
  d->feat.clear();
  d->label.clear();
  d->index.clear();
  return m;
}

DfTopo* df_topo_new() { return new DfTopo(); }
void df_topo_free(DfTopo* d) { delete d; }

long df_topo_feed(DfTopo* d, const char* buf, long len) {
  feed_lines(
      d->carry, d->in_quotes, buf, len,
      [d](const char* line, size_t L, bool hq) { d->on_line(line, L, hq); },
      [d]() { ++d->errors; });
  return long(d->src.size());
}

void df_topo_finish(DfTopo* d) { d->finish(); }
long df_topo_rows(DfTopo* d) { return long(d->row); }
long df_topo_num_nodes(DfTopo* d) { return long(d->node_ids.size()); }
long df_topo_num_edges(DfTopo* d) { return long(d->src.size()); }
long df_topo_errors(DfTopo* d) { return long(d->errors); }

long df_topo_node_ids_size(DfTopo* d) {
  long n = 0;
  for (const auto& s : d->node_ids) n += long(s.size()) + 1;  // '\n'-joined
  return n;
}

void df_topo_export_nodes(DfTopo* d, char* ids, float* is_seed, float* tcp,
                          float* utcp) {
  char* p = ids;
  for (const auto& s : d->node_ids) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
  memcpy(is_seed, d->is_seed.data(), d->is_seed.size() * sizeof(float));
  memcpy(tcp, d->tcp.data(), d->tcp.size() * sizeof(float));
  memcpy(utcp, d->utcp.data(), d->utcp.size() * sizeof(float));
}

void df_topo_export_edges(DfTopo* d, int32_t* src, int32_t* dst,
                          double* rtt_ns) {
  memcpy(src, d->src.data(), d->src.size() * sizeof(int32_t));
  memcpy(dst, d->dst.data(), d->dst.size() * sizeof(int32_t));
  memcpy(rtt_ns, d->rtt_ns.data(), d->rtt_ns.size() * sizeof(double));
}

}  // extern "C"
