// Native ingestion hot path: fused CSV decode + feature extraction.
//
// The reference left its training core a stub, so its ingestion edge is a
// 128MiB-chunk gRPC upload into CSV files (reference
// trainer/storage/storage.go:44-148); the TPU rebuild's north star (1B
// download records in <10min ⇒ ~1.7M rec/s sustained) makes the Python
// csv/numpy decode the bottleneck. This library streams the trainer's
// concatenated-CSV dataset files and emits training tensors directly:
//
//  - DfPairs: download records → (download,parent) pair features [M,12]
//    + log-cost labels, byte-identical semantics to
//    schema/features.extract_pair_features (the Python fallback).
//  - DfTopo: networktopology records → interned host nodes + probe edge
//    list, matching schema/features.build_probe_graph's interning and
//    last-write-wins edge semantics.
//
// CSV dialect: RFC4180 quotes (python csv.writer). Embedded header lines
// (every upload round re-sends one, trainer service demux) are detected by
// first-column == first header column and re-resolve the column mapping,
// so schema drift between scheduler versions is tolerated per-chunk.
//
// C ABI only — bound from Python via ctypes (schema/native.py).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#if defined(__AVX2__) || defined(__F16C__)
#include <immintrin.h>
#endif

namespace {

constexpr int kMaxParents = 20;     // schema/records.py MAX_PARENTS
constexpr int kMaxPieces = 10;      // MAX_PIECES_PER_PARENT
constexpr int kMaxDestHosts = 5;    // MAX_DEST_HOSTS
constexpr int kFeatureDim = 18;     // features.MLP_FEATURE_DIM
constexpr int kMaxLocationDepth = 5;
constexpr double kNsPerMs = 1e6;

// ---------------------------------------------------------------------------
// CSV line splitter (RFC4180: quoted fields, "" escapes). Fields are
// returned as string_views into a scratch buffer owned by the caller; the
// unquote path rewrites in place.
// ---------------------------------------------------------------------------

struct FieldRef {
  const char* data;
  size_t len;
  std::string view() const { return std::string(data, len); }
  bool empty() const { return len == 0; }
  bool eq(const char* s) const {
    size_t n = strlen(s);
    return len == n && memcmp(data, s, n) == 0;
  }
};

// Splits one line (excluding trailing \n / \r\n) into fields. `scratch`
// backs unescaped quoted fields. Returns false on malformed quoting.
bool split_csv_line(const char* line, size_t len, std::vector<FieldRef>& out,
                    std::string& scratch) {
  out.clear();
  scratch.clear();
  // Reserve so scratch never reallocates mid-parse (FieldRefs point into it).
  scratch.reserve(len + 1);
  size_t i = 0;
  while (true) {
    if (i < len && line[i] == '"') {
      // quoted field → unescape into scratch
      size_t start = scratch.size();
      ++i;
      while (i < len) {
        if (line[i] == '"') {
          if (i + 1 < len && line[i + 1] == '"') {
            scratch.push_back('"');
            i += 2;
          } else {
            ++i;
            break;
          }
        } else {
          scratch.push_back(line[i++]);
        }
      }
      out.push_back({scratch.data() + start, scratch.size() - start});
      if (i < len) {
        if (line[i] != ',') return false;
        ++i;
        continue;
      }
      break;
    }
    size_t start = i;
    while (i < len && line[i] != ',') ++i;
    out.push_back({line + start, i - start});
    if (i < len) {
      ++i;  // skip comma
      continue;
    }
    break;
  }
  return true;
}

double to_num_slow(const char* p, size_t n) {
  char buf[64];
  size_t m = n < sizeof(buf) - 1 ? n : sizeof(buf) - 1;
  memcpy(buf, p, m);
  buf[m] = '\0';
  return strtod(buf, nullptr);
}

// Fast decimal parse for the hot path: [-]digits[.digits]; anything else
// (exponents, >18 digits, inf/nan) falls back to strtod. CSV numbers here
// are short host stats and ns costs, so the fast path covers ~all fields.
double parse_num(const char* p, size_t n) {
  if (n == 0) return 0.0;
  static const double kPow10[] = {1.0,    1e1,  1e2,  1e3,  1e4,  1e5,  1e6,
                                  1e7,    1e8,  1e9,  1e10, 1e11, 1e12, 1e13,
                                  1e14,   1e15, 1e16, 1e17, 1e18};
  size_t i = 0;
  bool neg = false;
  if (p[0] == '-') {
    neg = true;
    i = 1;
  }
  uint64_t ip = 0;
  size_t digits = 0;
  for (; i < n; ++i) {
    unsigned d = unsigned(p[i]) - '0';
    if (d > 9) break;
    ip = ip * 10 + d;
    if (++digits > 18) return to_num_slow(p, n);
  }
  if (digits == 0) return to_num_slow(p, n);
  if (i == n) return neg ? -double(ip) : double(ip);
  if (p[i] != '.') return to_num_slow(p, n);
  ++i;
  uint64_t fp = 0;
  size_t fd = 0;
  for (; i < n; ++i) {
    unsigned d = unsigned(p[i]) - '0';
    if (d > 9) break;
    if (fd < 18) {
      fp = fp * 10 + d;
      ++fd;
    }
  }
  if (i != n) return to_num_slow(p, n);
  double v = double(ip) + double(fp) / kPow10[fd];
  return neg ? -v : v;
}

double to_num(const FieldRef& f) { return parse_num(f.data, f.len); }

// Shared leading "|"-separated path depth / kMaxLocationDepth
// (features.location_affinity).
double location_affinity(const std::string& a, const std::string& b) {
  if (a.empty() || b.empty()) return 0.0;
  int depth = 0;
  size_t ia = 0, ib = 0;
  for (int d = 0; d < kMaxLocationDepth; ++d) {
    if (ia > a.size() || ib > b.size()) break;
    size_t ea = a.find('|', ia);
    size_t eb = b.find('|', ib);
    size_t la = (ea == std::string::npos ? a.size() : ea) - ia;
    size_t lb = (eb == std::string::npos ? b.size() : eb) - ib;
    if (la != lb || memcmp(a.data() + ia, b.data() + ib, la) != 0) break;
    ++depth;
    if (ea == std::string::npos || eb == std::string::npos) break;
    ia = ea + 1;
    ib = eb + 1;
  }
  return double(depth) / kMaxLocationDepth;
}

// ---------------------------------------------------------------------------
// Streaming record feeder: buffers partial records across feed() chunks.
// A newline inside an RFC4180 quoted field is data, not a record break, so
// quote parity is tracked across chunks (csv.writer quotes any field
// containing the quote char, so parity toggling on every '"' is exact for
// writer-produced files).
// ---------------------------------------------------------------------------

// Bounded carry: a legitimate record is tens of KB; a multi-megabyte
// carry means corrupt input (an unterminated quote swallowing the rest
// of the stream). Discard it, reset quote parity, resync at the next
// newline — corruption costs a bounded window, not the whole file.
constexpr size_t kMaxCarry = 8 * 1024 * 1024;

template <typename RowFn, typename DiscardFn>
void feed_lines(std::string& carry, bool& in_quotes, const char* buf, long len,
                RowFn&& on_line, DiscardFn&& on_discard) {
  long pos = 0;
  // Lazy quote tracking: quotes are rare (csv.writer only quotes fields
  // containing separators/quotes), so instead of scanning every line for
  // '"' we keep a cursor to the NEXT quote at-or-after `pos`. Lines that
  // end before it need no parity work and no per-line quote memchr —
  // the common case is then two byte passes total ('\n' here, ',' in the
  // row scanner) instead of four.
  long next_quote = -1;  // -1: unknown; len: none remaining
  auto quote_at_or_after = [&](long p) -> long {
    if (next_quote < p) {
      const char* qp =
          static_cast<const char*>(memchr(buf + p, '"', size_t(len - p)));
      next_quote = qp ? long(qp - buf) : len;
    }
    return next_quote;
  };
  while (pos < len) {
    const char* nl =
        static_cast<const char*>(memchr(buf + pos, '\n', size_t(len - pos)));
    long end = nl ? long(nl - buf) : len;
    // quote parity over [pos, end): all segment quotes precede the
    // newline, so parity-after tells whether the newline is data
    long q = quote_at_or_after(pos);
    bool has_quote = q < end;
    while (q < end) {
      in_quotes = !in_quotes;
      const char* qp = static_cast<const char*>(
          memchr(buf + q + 1, '"', size_t(len - q - 1)));
      next_quote = qp ? long(qp - buf) : len;
      q = next_quote;
    }
    if (!nl) {  // chunk ends mid-record
      carry.append(buf + pos, size_t(len - pos));
      if (carry.size() > kMaxCarry) {
        carry.clear();
        in_quotes = false;
        on_discard();
      }
      return;
    }
    if (in_quotes) {  // newline inside a quoted field is data
      carry.append(buf + pos, size_t(end - pos + 1));
      if (carry.size() > kMaxCarry) {
        carry.clear();
        in_quotes = false;
        on_discard();
      }
      pos = end + 1;
      continue;
    }
    if (!carry.empty()) {
      carry.append(buf + pos, size_t(end - pos));
      size_t L = carry.size();
      if (L && carry[L - 1] == '\r') --L;
      on_line(carry.data(), L, true);  // conservative: carry may hold quotes
      carry.clear();
    } else {
      size_t L = size_t(end - pos);
      if (L && buf[end - 1] == '\r') --L;
      on_line(buf + pos, L, has_quote);
    }
    pos = end + 1;
  }
}

// ---------------------------------------------------------------------------
// Download-record pair decoder
// ---------------------------------------------------------------------------

enum PairCol : uint8_t {
  C_IGNORE = 0,
  C_TOTAL_PIECES,
  C_CHILD_IDC,
  C_CHILD_LOC,
  C_CHILD_CPU,
  C_CHILD_MEM,
  C_TASK_LEN,
  // every P_* kind must stay >= P_ID (the empty-slot fast-forward keys
  // on that ordering)
  P_ID,
  P_STATE,
  P_FIN,
  P_UPLOAD_COUNT,
  P_UPLOAD_FAILED,
  P_CUL,
  P_CUC,
  P_TYPE,
  P_IDC,
  P_LOC,
  P_CPU,
  P_MEM,
  P_TCP,
  P_UTCP,
  P_DISK,
  P_CPU_PROC,
  P_MEM_AVAIL,
  P_MEM_TOTAL,
  P_INODES,
  P_PIECE_COST,
};

struct ColAction {
  uint8_t kind = C_IGNORE;
  uint8_t parent = 0;
  uint8_t piece = 0;
};

struct ParentScratch {
  bool has_id = false;
  bool succeeded = false;
  bool is_seed = false;
  std::string idc, loc;
  double fin = 0, upload_count = 0, upload_failed = 0, cul = 0, cuc = 0;
  double cpu = 0, mem = 0, tcp = 0, utcp = 0, disk = 0;
  double cpu_proc = 0, mem_avail = 0, mem_total = 0, inodes = 0;
  double piece_cost[kMaxPieces];
  void reset() {
    has_id = succeeded = is_seed = false;
    idc.clear();
    loc.clear();
    fin = upload_count = upload_failed = cul = cuc = 0;
    cpu = mem = tcp = utcp = disk = 0;
    cpu_proc = mem_avail = mem_total = inodes = 0;
    memset(piece_cost, 0, sizeof(piece_cost));
  }
};

struct DfPairs {
  std::vector<ColAction> colmap;
  std::vector<uint32_t> hot_cols;  // ascending indices of non-ignored columns
  std::vector<uint32_t> skip_on_empty;  // hot-index jump when a P_ID is empty
  std::string header_col0;
  std::string carry;        // partial record across feed() chunks
  bool in_quotes = false;   // RFC4180 quote parity across chunks
  std::string scratch;      // unquote buffer
  std::vector<FieldRef> fields;
  ParentScratch parents[kMaxParents];
  std::string child_idc, child_loc;
  double total_pieces = 0;
  double child_cpu = 0, child_mem = 0, task_len = 0;
  int64_t row = 0;  // download-record counter (not counting headers)
  int64_t errors = 0;

  std::vector<float> feat;    // M * kFeatureDim
  std::vector<float> label;   // M
  std::vector<int32_t> index; // M — source download row

  void resolve_header(const std::vector<FieldRef>& hs) {
    colmap.assign(hs.size(), ColAction{});
    header_col0 = hs.empty() ? "" : hs[0].view();
    for (size_t c = 0; c < hs.size(); ++c) {
      std::string name = hs[c].view();
      ColAction a;
      if (name == "task.total_piece_count") {
        a.kind = C_TOTAL_PIECES;
      } else if (name == "task.content_length") {
        a.kind = C_TASK_LEN;
      } else if (name == "host.network.idc") {
        a.kind = C_CHILD_IDC;
      } else if (name == "host.network.location") {
        a.kind = C_CHILD_LOC;
      } else if (name == "host.cpu.percent") {
        a.kind = C_CHILD_CPU;
      } else if (name == "host.memory.used_percent") {
        a.kind = C_CHILD_MEM;
      } else if (name.rfind("parents.", 0) == 0) {
        const char* p = name.c_str() + 8;
        char* end;
        long slot = strtol(p, &end, 10);
        if (end == p || *end != '.' || slot < 0 || slot >= kMaxParents) {
          colmap[c] = a;
          continue;
        }
        std::string rest(end + 1);
        a.parent = uint8_t(slot);
        if (rest == "id") a.kind = P_ID;
        else if (rest == "state") a.kind = P_STATE;
        else if (rest == "finished_piece_count") a.kind = P_FIN;
        else if (rest == "host.upload_count") a.kind = P_UPLOAD_COUNT;
        else if (rest == "host.upload_failed_count") a.kind = P_UPLOAD_FAILED;
        else if (rest == "host.concurrent_upload_limit") a.kind = P_CUL;
        else if (rest == "host.concurrent_upload_count") a.kind = P_CUC;
        else if (rest == "host.type") a.kind = P_TYPE;
        else if (rest == "host.network.idc") a.kind = P_IDC;
        else if (rest == "host.network.location") a.kind = P_LOC;
        else if (rest == "host.cpu.percent") a.kind = P_CPU;
        else if (rest == "host.memory.used_percent") a.kind = P_MEM;
        else if (rest == "host.network.tcp_connection_count") a.kind = P_TCP;
        else if (rest == "host.network.upload_tcp_connection_count") a.kind = P_UTCP;
        else if (rest == "host.disk.used_percent") a.kind = P_DISK;
        else if (rest == "host.cpu.process_percent") a.kind = P_CPU_PROC;
        else if (rest == "host.memory.available") a.kind = P_MEM_AVAIL;
        else if (rest == "host.memory.total") a.kind = P_MEM_TOTAL;
        else if (rest == "host.disk.inodes_used_percent") a.kind = P_INODES;
        else if (rest.rfind("pieces.", 0) == 0) {
          const char* q = rest.c_str() + 7;
          long pj = strtol(q, &end, 10);
          if (end != q && strcmp(end, ".cost") == 0 && pj >= 0 && pj < kMaxPieces) {
            a.kind = P_PIECE_COST;
            a.piece = uint8_t(pj);
          }
        }
      }
      colmap[c] = a;
    }
    hot_cols.clear();
    for (size_t c = 0; c < colmap.size(); ++c)
      if (colmap[c].kind != C_IGNORE) hot_cols.push_back(uint32_t(c));
    // Empty-slot fast-forward: when a parent's id column is empty the
    // whole slot is padding, so the scan can jump to the first hot column
    // NOT belonging to that parent. This is what keeps 20-slot padded
    // rows near the cost of their populated prefix.
    skip_on_empty.assign(hot_cols.size(), 0);
    for (size_t hi = 0; hi < hot_cols.size(); ++hi) {
      const ColAction a = colmap[hot_cols[hi]];
      if (a.kind != P_ID) continue;
      size_t hj = hi + 1;
      while (hj < hot_cols.size()) {
        const ColAction b = colmap[hot_cols[hj]];
        const bool same_parent = b.kind >= P_ID && b.parent == a.parent;
        if (!same_parent) break;
        ++hj;
      }
      skip_on_empty[hi] = uint32_t(hj);
    }
  }

  inline void dispatch(const ColAction a, const char* p, size_t n) {
    // empty fields (padding parent slots) keep their reset() defaults —
    // skipping them is what makes padded 20-slot rows cheap
    if (n == 0) return;
    const FieldRef f{p, n};
    ParentScratch& ps = parents[a.parent];
    switch (a.kind) {
      case C_TOTAL_PIECES: total_pieces = to_num(f); break;
      case C_TASK_LEN: task_len = to_num(f); break;
      case C_CHILD_IDC: child_idc.assign(p, n); break;
      case C_CHILD_LOC: child_loc.assign(p, n); break;
      case C_CHILD_CPU: child_cpu = to_num(f); break;
      case C_CHILD_MEM: child_mem = to_num(f); break;
      case P_ID: ps.has_id = true; break;
      case P_STATE: ps.succeeded = f.eq("Succeeded"); break;
      case P_FIN: ps.fin = to_num(f); break;
      case P_UPLOAD_COUNT: ps.upload_count = to_num(f); break;
      case P_UPLOAD_FAILED: ps.upload_failed = to_num(f); break;
      case P_CUL: ps.cul = to_num(f); break;
      case P_CUC: ps.cuc = to_num(f); break;
      case P_TYPE: ps.is_seed = !f.eq("normal"); break;
      case P_IDC: ps.idc.assign(p, n); break;
      case P_LOC: ps.loc.assign(p, n); break;
      case P_CPU: ps.cpu = to_num(f); break;
      case P_MEM: ps.mem = to_num(f); break;
      case P_TCP: ps.tcp = to_num(f); break;
      case P_UTCP: ps.utcp = to_num(f); break;
      case P_DISK: ps.disk = to_num(f); break;
      case P_CPU_PROC: ps.cpu_proc = to_num(f); break;
      case P_MEM_AVAIL: ps.mem_avail = to_num(f); break;
      case P_MEM_TOTAL: ps.mem_total = to_num(f); break;
      case P_INODES: ps.inodes = to_num(f); break;
      case P_PIECE_COST: ps.piece_cost[a.piece] = to_num(f); break;
      default: break;
    }
  }

  void reset_scratch() {
    total_pieces = 0;
    child_cpu = child_mem = task_len = 0;
    child_idc.clear();
    child_loc.clear();
    for (auto& p : parents) p.reset();
  }

  bool looks_like_header(const char* line, size_t len) const {
    const size_t h = header_col0.size();
    return h && len >= h && memcmp(line, header_col0.data(), h) == 0 &&
           (len == h || line[h] == ',');
  }

  void on_line(const char* line, size_t len, bool has_quote = true) {
    if (len == 0) return;
    if (colmap.empty() || has_quote || looks_like_header(line, len)) {
      on_line_slow(line, len);
      return;
    }
    reset_scratch();
    scan_row_fast(line, len);
    emit_row();
    ++row;
  }

  // Header lines and RFC4180-quoted rows: full split + mapped walk.
  void on_line_slow(const char* line, size_t len) {
    if (!split_csv_line(line, len, fields, scratch)) {
      ++errors;
      return;
    }
    // Header detection: no mapping yet, or first column repeats the
    // header's first column name (embedded header of a later upload).
    if (colmap.empty() || (!fields.empty() && !header_col0.empty() &&
                           fields[0].eq(header_col0.c_str()))) {
      resolve_header(fields);
      return;
    }
    reset_scratch();
    size_t n = fields.size() < colmap.size() ? fields.size() : colmap.size();
    for (size_t c = 0; c < n; ++c) {
      const ColAction a = colmap[c];
      if (a.kind == C_IGNORE) continue;
      dispatch(a, fields[c].data, fields[c].len);
    }
    emit_row();
    ++row;
  }

  // Tail short-circuit: called when a parent id column is empty. If every
  // byte from `from` up to the line's second-to-last comma is a comma,
  // then all remaining parent columns are empty (only the trailing
  // created_at/updated_at — never hot — carry data), so the scan can stop
  // for the whole row. Exact for any input: a later parent that DID have
  // data would put a non-comma byte inside the checked span (its id and
  // any piece-cost column are never the final two fields — the schema
  // keeps them ≥2 columns apart), failing the check and falling back to
  // the normal scan.
  //
  // Honest scope note: OUR csv.DictWriter serializes padding slots as
  // "0"s (flatten()'s default ParentRecord), so on self-produced files
  // this check always fails and each padded row pays one extra O(tail)
  // scan (`tried_tail` bounds it to once per row). It fires — and pays
  // off — on writers that leave padding columns EMPTY, e.g. files from
  // other producers on the same schema. Kept for that case; remove the
  // call sites if all inputs are known self-produced.
  static bool tail_is_padding(const char* line, size_t len, size_t from) {
    long p_last = -1, p_prev = -1;
    for (long j = long(len) - 1; j >= long(from); --j) {
      if (line[j] == ',') {
        if (p_last < 0) {
          p_last = j;
        } else {
          p_prev = j;
          break;
        }
      }
    }
    if (p_prev < 0) return false;
    size_t i = from;
#if defined(__AVX2__)
    const __m256i commas = _mm256_set1_epi8(',');
    for (; i + 32 <= size_t(p_prev); i += 32) {
      const __m256i v =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(line + i));
      if (uint32_t(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, commas))) !=
          0xffffffffu)
        return false;
    }
#endif
    for (; i < size_t(p_prev); ++i)
      if (line[i] != ',') return false;
    return true;
  }

  // Unquoted data rows (the overwhelmingly common case): one pass over the
  // line, finding commas 32 bytes at a time (AVX2) and materializing only
  // the ~hot columns the feature extractor reads. Runs of ignored columns
  // — including the empty padding parent slots — are consumed by popcount
  // without touching individual fields.
  void scan_row_fast(const char* line, size_t len) {
    const size_t nhot = hot_cols.size();
    size_t hi = 0;
    uint32_t next_hot = nhot ? hot_cols[0] : 0xffffffffu;
    uint32_t c = 0;        // current column index
    size_t field_start = 0;
    size_t i = 0;
    bool tried_tail = false;  // attempt the tail short-circuit once per row
#if defined(__AVX2__)
    const __m256i commas = _mm256_set1_epi8(',');
    while (i + 32 <= len && hi < nhot) {
      const __m256i v =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(line + i));
      uint32_t m =
          uint32_t(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, commas)));
      if (m == 0) {
        i += 32;
        continue;
      }
      uint32_t cnt = uint32_t(__builtin_popcount(m));
      if (c + cnt < next_hot) {
        // every comma in this block belongs to ignored columns — consume
        // them in bulk; the in-progress field after the block starts
        // right past the last comma
        c += cnt;
        field_start = i + size_t(31 - __builtin_clz(m)) + 1;
        i += 32;
        continue;
      }
#if defined(__BMI2__)
      // The block holds ≥1 hot-column boundary. Jump straight to each hot
      // field's bounding commas with pdep (deposit selects the k-th set
      // bit) instead of iterating every comma — populated rows have ~7×
      // more commas than hot columns.
      while (true) {
        // next_hot's field ends at overall comma #next_hot, which is the
        // (next_hot - c)-th comma (0-based) of the remaining mask
        uint32_t k = next_hot - c;
        if (k >= cnt) {  // ends beyond this block: consume the rest
          c += cnt;
          field_start = i + size_t(31 - __builtin_clz(m)) + 1;
          break;
        }
        if (k > 0) {  // field starts after the (k-1)-th remaining comma
          const uint32_t before = uint32_t(_pdep_u32(1u << (k - 1), m));
          field_start = i + size_t(__builtin_ctz(before)) + 1;
        }
        const uint32_t at = uint32_t(_pdep_u32(1u << k, m));
        const size_t pos = i + size_t(__builtin_ctz(at));
        const size_t flen = pos - field_start;
        if (flen == 0 && skip_on_empty[hi]) {
          if (!tried_tail) {
            tried_tail = true;
            if (tail_is_padding(line, len, pos + 1)) return;
          }
          hi = skip_on_empty[hi];  // empty parent id → skip the slot
        } else {
          dispatch(colmap[c + k], line + field_start, flen);
          ++hi;
        }
        next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
        // consume commas up to and including the field-ending one
        const uint32_t used = k + 1;
        c += used;
        cnt -= used;
        field_start = pos + 1;
        if (hi >= nhot) return;
        if (cnt == 0) break;  // before the shift: `<< 32` would be UB
        m = uint32_t(_pdep_u32(0xffffffffu << used, m)) & m;
      }
#else
      while (m) {
        const uint32_t b = uint32_t(__builtin_ctz(m));
        m &= m - 1;
        const size_t pos = i + b;
        if (c == next_hot) {
          const size_t flen = pos - field_start;
          if (flen == 0 && skip_on_empty[hi]) {
            if (!tried_tail) {
              tried_tail = true;
              if (tail_is_padding(line, len, pos + 1)) return;
            }
            hi = skip_on_empty[hi];  // empty parent id → skip the slot
          } else {
            dispatch(colmap[c], line + field_start, flen);
            ++hi;
          }
          next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
        }
        ++c;
        field_start = pos + 1;
        if (hi >= nhot) return;
      }
#endif
      i += 32;
    }
#endif
    for (; i < len && hi < nhot; ++i) {
      if (line[i] != ',') continue;
      if (c == next_hot) {
        const size_t flen = i - field_start;
        if (flen == 0 && skip_on_empty[hi]) {
          if (!tried_tail) {
            tried_tail = true;
            if (tail_is_padding(line, len, i + 1)) return;
          }
          hi = skip_on_empty[hi];
        } else {
          dispatch(colmap[c], line + field_start, flen);
          ++hi;
        }
        next_hot = hi < nhot ? hot_cols[hi] : 0xffffffffu;
      }
      ++c;
      field_start = i + 1;
    }
    // trailing field (no comma after the last column)
    if (hi < nhot && c == next_hot && field_start <= len)
      dispatch(colmap[c], line + field_start, len - field_start);
  }

  void emit_row() {
    double total = total_pieces > 1.0 ? total_pieces : 1.0;
    for (int s = 0; s < kMaxParents; ++s) {
      ParentScratch& p = parents[s];
      if (!p.has_id) continue;
      double cost_sum = 0;
      int cost_cnt = 0;
      for (double c : p.piece_cost)
        if (c > 0) {
          cost_sum += c;
          ++cost_cnt;
        }
      if (cost_cnt == 0) continue;  // mask: valid_parent & (cost_cnt > 0)

      double finished_ratio = p.fin / total;
      if (finished_ratio < 0) finished_ratio = 0;
      if (finished_ratio > 1) finished_ratio = 1;
      double upc = p.upload_count > 1.0 ? p.upload_count : 1.0;
      double upload_success = (p.upload_count - p.upload_failed) / upc;
      double cul = p.cul > 1.0 ? p.cul : 1.0;
      double free_upload = 1.0 - p.cuc / cul;
      if (free_upload < 0) free_upload = 0;
      if (free_upload > 1) free_upload = 1;
      bool idc_match = !p.idc.empty() && p.idc == child_idc;

      double mem_total = p.mem_total > 1.0 ? p.mem_total : 1.0;
      const double f[kFeatureDim] = {
          finished_ratio,
          upload_success,
          free_upload,
          p.is_seed ? 1.0 : 0.0,
          idc_match ? 1.0 : 0.0,
          location_affinity(child_loc, p.loc),
          p.cpu / 100.0,
          p.mem / 100.0,
          log1p(p.tcp) / 10.0,
          log1p(p.utcp) / 10.0,
          p.disk / 100.0,
          p.succeeded ? 1.0 : 0.0,
          p.cpu_proc / 100.0,
          p.mem_avail / mem_total,
          p.inodes / 100.0,
          child_cpu / 100.0,
          child_mem / 100.0,
          log1p(task_len > 0 ? task_len : 0.0) / 30.0,
      };
      for (double v : f) feat.push_back(float(v));
      double mean_cost_ms = cost_sum / cost_cnt / kNsPerMs;
      label.push_back(float(log1p(mean_cost_ms)));
      index.push_back(int32_t(row));
    }
  }

  // End-of-file boundary: flush a trailing record that has no newline and
  // reset quote parity, so concatenating the next file (or pass) cannot
  // bleed this file's tail into its first record. Safe to call once per
  // file mid-stream — parser column mapping survives.
  void finish() {
    if (!carry.empty()) {
      std::string tail;
      tail.swap(carry);
      size_t L = tail.size();
      if (L && tail[L - 1] == '\r') --L;
      on_line(tail.data(), L);
    }
    in_quotes = false;
  }
};

// ---------------------------------------------------------------------------
// Network-topology graph decoder
// ---------------------------------------------------------------------------

enum TopoCol : uint8_t {
  T_IGNORE = 0,
  T_SRC_ID,
  T_SRC_TYPE,
  T_SRC_TCP,
  T_SRC_UTCP,
  D_ID,
  D_TYPE,
  D_TCP,
  D_UTCP,
  D_RTT,
};

struct TopoColAction {
  uint8_t kind = T_IGNORE;
  uint8_t dest = 0;
};

struct DestScratch {
  std::string id;
  bool is_seed = false;
  double tcp = 0, utcp = 0, rtt = 0;
  void reset() {
    id.clear();
    is_seed = false;
    tcp = utcp = rtt = 0;
  }
};

struct DfTopo {
  std::vector<TopoColAction> colmap;
  std::string header_col0;
  std::string carry, scratch;
  bool in_quotes = false;   // RFC4180 quote parity across chunks
  std::vector<FieldRef> fields;
  int64_t errors = 0;
  int64_t row = 0;          // topology-record counter (not counting headers)

  // interned nodes (first-appearance order, like the Python dict)
  std::unordered_map<std::string, int32_t> index;
  std::vector<std::string> node_ids;
  std::vector<float> is_seed, tcp, utcp;

  // edges, insertion-ordered with last-write-wins RTT
  std::unordered_map<uint64_t, size_t> edge_index;
  std::vector<int32_t> src, dst;
  std::vector<double> rtt_ns;

  std::string src_id, src_type;
  double src_tcp = 0, src_utcp = 0;
  DestScratch dests[kMaxDestHosts];

  int32_t intern(const std::string& hid, bool seed, double t, double u) {
    auto it = index.find(hid);
    if (it == index.end()) {
      int32_t idx = int32_t(node_ids.size());
      index.emplace(hid, idx);
      node_ids.push_back(hid);
      is_seed.push_back(seed ? 1.0f : 0.0f);
      tcp.push_back(float(t));
      utcp.push_back(float(u));
      return idx;
    }
    // refresh load stats, last write wins (features.build_probe_graph)
    tcp[it->second] = float(t);
    utcp[it->second] = float(u);
    return it->second;
  }

  void resolve_header(const std::vector<FieldRef>& hs) {
    colmap.assign(hs.size(), TopoColAction{});
    header_col0 = hs.empty() ? "" : hs[0].view();
    for (size_t c = 0; c < hs.size(); ++c) {
      std::string name = hs[c].view();
      TopoColAction a;
      if (name == "host.id") a.kind = T_SRC_ID;
      else if (name == "host.type") a.kind = T_SRC_TYPE;
      else if (name == "host.network.tcp_connection_count") a.kind = T_SRC_TCP;
      else if (name == "host.network.upload_tcp_connection_count") a.kind = T_SRC_UTCP;
      else if (name.rfind("dest_hosts.", 0) == 0) {
        const char* p = name.c_str() + 11;
        char* end;
        long slot = strtol(p, &end, 10);
        if (end == p || *end != '.' || slot < 0 || slot >= kMaxDestHosts) {
          colmap[c] = a;
          continue;
        }
        std::string rest(end + 1);
        a.dest = uint8_t(slot);
        if (rest == "id") a.kind = D_ID;
        else if (rest == "type") a.kind = D_TYPE;
        else if (rest == "network.tcp_connection_count") a.kind = D_TCP;
        else if (rest == "network.upload_tcp_connection_count") a.kind = D_UTCP;
        else if (rest == "probes.average_rtt") a.kind = D_RTT;
      }
      colmap[c] = a;
    }
  }

  void on_line(const char* line, size_t len, bool = true) {
    if (len == 0) return;
    if (!split_csv_line(line, len, fields, scratch)) {
      ++errors;
      return;
    }
    if (colmap.empty() || (!fields.empty() && !header_col0.empty() &&
                           fields[0].eq(header_col0.c_str()))) {
      resolve_header(fields);
      return;
    }
    src_id.clear();
    src_type.clear();
    src_tcp = src_utcp = 0;
    for (auto& d : dests) d.reset();

    size_t n = fields.size() < colmap.size() ? fields.size() : colmap.size();
    for (size_t c = 0; c < n; ++c) {
      const TopoColAction a = colmap[c];
      if (a.kind == T_IGNORE) continue;
      const FieldRef& f = fields[c];
      DestScratch& d = dests[a.dest];
      switch (a.kind) {
        case T_SRC_ID: src_id = f.view(); break;
        case T_SRC_TYPE: src_type = f.view(); break;
        case T_SRC_TCP: src_tcp = to_num(f); break;
        case T_SRC_UTCP: src_utcp = to_num(f); break;
        case D_ID: d.id = f.view(); break;
        case D_TYPE: d.is_seed = !f.empty() && !f.eq("normal"); break;
        case D_TCP: d.tcp = to_num(f); break;
        case D_UTCP: d.utcp = to_num(f); break;
        case D_RTT: d.rtt = to_num(f); break;
        default: break;
      }
    }
    ++row;
    // the Python spec (features.build_probe_graph) interns the src
    // UNCONDITIONALLY — even an empty id becomes a node — and skips
    // only empty dests; matching exactly keeps node indices aligned
    // between the native and numpy paths (the parity contract)
    bool src_seed = !src_type.empty() && src_type != "normal";
    int32_t s = intern(src_id, src_seed, src_tcp, src_utcp);
    for (auto& d : dests) {
      if (d.id.empty()) continue;
      int32_t t = intern(d.id, d.is_seed, d.tcp, d.utcp);
      if (d.rtt > 0) {
        uint64_t key = (uint64_t(uint32_t(s)) << 32) | uint32_t(t);
        auto it = edge_index.find(key);
        if (it == edge_index.end()) {
          edge_index.emplace(key, src.size());
          src.push_back(s);
          dst.push_back(t);
          rtt_ns.push_back(d.rtt);
        } else {
          rtt_ns[it->second] = d.rtt;
        }
      }
    }
  }

  void finish() {
    if (!carry.empty()) {
      std::string tail;
      tail.swap(carry);
      size_t L = tail.size();
      if (L && tail[L - 1] == '\r') --L;
      on_line(tail.data(), L);
    }
    in_quotes = false;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

DfPairs* df_pairs_new() { return new DfPairs(); }
void df_pairs_free(DfPairs* d) { delete d; }

long df_pairs_feed(DfPairs* d, const char* buf, long len) {
  feed_lines(
      d->carry, d->in_quotes, buf, len,
      [d](const char* line, size_t L, bool hq) { d->on_line(line, L, hq); },
      [d]() { ++d->errors; });
  return long(d->label.size());
}

void df_pairs_finish(DfPairs* d) { d->finish(); }
long df_pairs_count(DfPairs* d) { return long(d->label.size()); }
long df_pairs_rows(DfPairs* d) { return long(d->row); }
long df_pairs_errors(DfPairs* d) { return long(d->errors); }

void df_pairs_export(DfPairs* d, float* feat, float* label, int32_t* idx) {
  memcpy(feat, d->feat.data(), d->feat.size() * sizeof(float));
  memcpy(label, d->label.data(), d->label.size() * sizeof(float));
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
}

// Streaming variant: export the pairs accumulated since the last take and
// clear the buffers, so a long decode runs in bounded memory (caller
// sizes the output with df_pairs_count between feed and take — same
// thread drives both). Parser state (carry, colmap) is untouched, so
// takes interleave freely with feeds mid-stream.
long df_pairs_take(DfPairs* d, float* feat, float* label, int32_t* idx) {
  long m = long(d->label.size());
  memcpy(feat, d->feat.data(), d->feat.size() * sizeof(float));
  memcpy(label, d->label.data(), d->label.size() * sizeof(float));
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
  d->feat.clear();
  d->label.clear();
  d->index.clear();
  return m;
}

// f32 → IEEE half (round-to-nearest-even) for the reduced-precision
// device feed: converting at take time keeps the vectors cache-hot and
// moves the cast off the GIL-held Python packing loop (the consumer is
// the bottleneck on small hosts). F16C does 8 lanes per instruction when
// the build arch has it; the scalar path is the bit-exact fallback.
static inline uint16_t f32_to_f16(float v) {
  uint32_t x;
  memcpy(&x, &v, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  int32_t exp = int32_t((x >> 23) & 0xff) - 127 + 15;
  uint32_t mant = x & 0x7fffffu;
  if (exp >= 31) {
    // inf/overflow → ±inf; NaN keeps a mantissa bit (strtod parses the
    // literal "nan" in CSV stats, and the F16C path / np.float16 both
    // preserve it — silently turning NaN into inf would make the
    // half-precision feed differ by build architecture)
    bool is_nan = (int32_t((x >> 23) & 0xff) == 0xff) && mant != 0;
    return uint16_t(sign | 0x7c00u | (is_nan ? 0x0200u : 0u));
  }
  if (exp <= 0) {
    if (exp < -10) return uint16_t(sign);
    mant |= 0x800000u;
    uint32_t shift = uint32_t(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1))) ++half;
    return uint16_t(sign | half);
  }
  uint32_t half = uint32_t(exp << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;
  return uint16_t(sign | half);
}

static void f32_to_f16_buf(const float* in, uint16_t* out, size_t n) {
#if defined(__F16C__)
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 v = _mm256_loadu_ps(in + i);
    __m128i h = _mm256_cvtps_ph(v, _MM_FROUND_TO_NEAREST_INT);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + i), h);
  }
  for (; i < n; ++i) out[i] = f32_to_f16(in[i]);
#else
  for (size_t i = 0; i < n; ++i) out[i] = f32_to_f16(in[i]);
#endif
}

// ABI handshake: the binding layer refuses a library whose feature
// width disagrees with the python schema (a stale prebuilt .so via
// DF_NATIVE_LIB would otherwise fill misaligned tensors silently).
long df_feature_dim() { return kFeatureDim; }

long df_pairs_take_half(DfPairs* d, uint16_t* feat, uint16_t* label, int32_t* idx) {
  long m = long(d->label.size());
  f32_to_f16_buf(d->feat.data(), feat, d->feat.size());
  f32_to_f16_buf(d->label.data(), label, d->label.size());
  memcpy(idx, d->index.data(), d->index.size() * sizeof(int32_t));
  d->feat.clear();
  d->label.clear();
  d->index.clear();
  return m;
}

DfTopo* df_topo_new() { return new DfTopo(); }
void df_topo_free(DfTopo* d) { delete d; }

long df_topo_feed(DfTopo* d, const char* buf, long len) {
  feed_lines(
      d->carry, d->in_quotes, buf, len,
      [d](const char* line, size_t L, bool hq) { d->on_line(line, L, hq); },
      [d]() { ++d->errors; });
  return long(d->src.size());
}

void df_topo_finish(DfTopo* d) { d->finish(); }
long df_topo_rows(DfTopo* d) { return long(d->row); }
long df_topo_num_nodes(DfTopo* d) { return long(d->node_ids.size()); }
long df_topo_num_edges(DfTopo* d) { return long(d->src.size()); }
long df_topo_errors(DfTopo* d) { return long(d->errors); }

long df_topo_node_ids_size(DfTopo* d) {
  long n = 0;
  for (const auto& s : d->node_ids) n += long(s.size()) + 1;  // '\n'-joined
  return n;
}

void df_topo_export_nodes(DfTopo* d, char* ids, float* is_seed, float* tcp,
                          float* utcp) {
  char* p = ids;
  for (const auto& s : d->node_ids) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
  memcpy(is_seed, d->is_seed.data(), d->is_seed.size() * sizeof(float));
  memcpy(tcp, d->tcp.data(), d->tcp.size() * sizeof(float));
  memcpy(utcp, d->utcp.data(), d->utcp.size() * sizeof(float));
}

void df_topo_export_edges(DfTopo* d, int32_t* src, int32_t* dst,
                          double* rtt_ns) {
  memcpy(src, d->src.data(), d->src.size() * sizeof(int32_t));
  memcpy(dst, d->dst.data(), d->dst.size() * sizeof(int32_t));
  memcpy(rtt_ns, d->rtt_ns.data(), d->rtt_ns.size() * sizeof(double));
}

}  // extern "C"
